"""Wire front end for swarmserve: external client processes submit over
the interop shm rings (docs/SERVICE.md §wire protocol; ROADMAP open
item 2(a)).

The serving layer was deliberately in-process through PR 7; this module
is the transport boundary. The design reuses what already exists
instead of inventing a protocol:

- **transport**: `interop.transport.Channel` — the named SPSC
  shared-memory rings (`native/shmring.cpp`), one ring per direction
  per connection, plus one well-known *control* ring for handshakes;
- **wire format**: the journal's codec-framed records
  (`resilience.checkpoint.dumps/loads` — magic, version, CRC,
  length-prefixed array table). A request ON THE WIRE is byte-for-byte
  the record the journal stores, so there is exactly one serialization
  surface to version and one CRC to trust. Versioning rides the frame's
  ``format_version`` plus a ``wire_version`` manifest field checked at
  hello time.

Connection lifecycle (client-created rings, server-owned control)::

    server:  WireServer(service, base)        # creates {base}.ctl
    client:  WireClient(base)                 # creates {base}.{cid}.c2s
                                              #     and {base}.{cid}.s2c,
                                              # then HELLO on the ctl ring
    client:  submit(...) -> Ticket            # wire.submit -> accept/
                                              # reject frame
    server:  streams wire.event / wire.result frames back per request
    client:  close()                          # BYE (clean) — or just die

Failure semantics (the loud-disconnect contract):

- a frame that fails the codec CRC (or does not parse) is REJECTED with
  a loud log + ``wire_crc_rejected_total`` — never partially applied;
- a client that stops talking (no submit/ping within
  ``client_lease_s``) is declared dead: its entries are cancelled with
  a structured ``cancelled`` error — still-QUEUED ones immediately,
  RESIDENT ones only at their next chunk boundary — never the running
  batch mid-kernel; the terminal results are journaled and their
  delivery dropped loudly;
- per-connection deadlines: every submit may carry ``deadline_s``; the
  connection's ``default_deadline_s`` applies otherwise, so one slow
  client cannot park unbounded work.

The server is a thin adapter: admission, fairness, journaling, failover
and every promise stay in `SwarmService` — a wire client gets exactly
the in-process semantics, one process boundary later.
"""
from __future__ import annotations

import contextlib
import fcntl
import queue as queuelib
import threading
import time
import uuid
from pathlib import Path
from typing import Optional

from aclswarm_tpu.interop import transport
from aclswarm_tpu.resilience import checkpoint as ckptlib
from aclswarm_tpu.serve.api import (E_QUEUE_FULL, E_SHUTDOWN, FAILED,
                                    ChunkEvent, RejectedError, Result,
                                    ServeError, Ticket)
from aclswarm_tpu.serve.api import _SENTINEL as _TICKET_SENTINEL
from aclswarm_tpu.telemetry import mint_trace_id
from aclswarm_tpu.utils import get_logger

WIRE_VERSION = 1
# frame kinds (the manifest's `kind` field — same slot the journal uses)
K_HELLO = "wire_hello"
K_HELLO_ACK = "wire_hello_ack"
K_SUBMIT = "wire_submit"
K_ACCEPT = "wire_accept"
K_REJECT = "wire_reject"
K_EVENT = "wire_event"
K_RESULT = "wire_result"
K_ERROR = "wire_error"
K_PING = "wire_ping"
K_BYE = "wire_bye"

RING_CAPACITY = 1 << 20


@contextlib.contextmanager
def _ctl_writer_lock(base: str):
    """Cross-process writer lock for the shared control ring. The shm
    rings are strictly SINGLE-producer (`native/shmring.cpp` uses plain
    non-CAS head writes), but every client writes its HELLO to the one
    well-known ctl ring — two clients connecting concurrently would
    interleave their head updates and misframe the ring for everyone
    after. A flock on a well-known lock file serializes the (rare,
    tiny) ctl writes; connection rings stay lock-free SPSC."""
    path = Path("/dev/shm") if Path("/dev/shm").is_dir() \
        else Path("/tmp")
    lock = path / f"aclswarm.{base.strip('/')}.ctl.lock"
    with open(lock, "a+b") as f:
        fcntl.flock(f.fileno(), fcntl.LOCK_EX)
        try:
            yield
        finally:
            fcntl.flock(f.fileno(), fcntl.LOCK_UN)


def _frame(kind: str, payload: dict, **extra) -> bytes:
    return ckptlib.dumps(payload, ckptlib.make_manifest(
        kind, "-", chunk=0, wire_version=WIRE_VERSION, **extra))


def _send(channel, frame: bytes, grace_s: float = 2.0, log=None,
          what: str = "frame") -> bool:
    """Backpressure-bounded raw send; a drop after the grace is LOUD
    (the receiving side stopped draining — a dead or wedged peer).
    The loop is `transport.send_bytes_reliable` — one home for the
    bounded-send semantics."""
    return transport.send_bytes_reliable(channel, frame,
                                         grace_s=grace_s, poll_s=0.001,
                                         log=log, what=what)


class _Conn:
    """Server-side state for one client connection."""

    def __init__(self, cid: str, c2s, s2c):
        self.cid = cid
        self.c2s = c2s
        self.s2c = s2c
        self.last_seen = time.monotonic()
        self.pending: dict[str, Ticket] = {}    # rid -> live ticket
        self.dead = False


class WireServer:
    """Serve `SwarmService` requests to external processes over shm
    rings. One dispatcher thread owns every ring (SPSC discipline: the
    server is the single reader of ctl + every c2s, the single writer
    of every s2c)."""

    def __init__(self, service, base: str = "aclswarm-serve", *,
                 client_lease_s: float = 10.0,
                 default_deadline_s: Optional[float] = None,
                 poll_s: float = 0.002, log=None):
        self.svc = service
        self.base = base
        self.client_lease_s = float(client_lease_s)
        self.default_deadline_s = default_deadline_s
        self.poll_s = float(poll_s)
        self.log = log or get_logger("serve.wire")
        self._ctl = transport.Channel(f"{base}.ctl", create=True,
                                      capacity=RING_CAPACITY)
        self._conns: dict[str, _Conn] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="swarmserve-wire")
        self._thread.start()

    # ------------------------------------------------------------- loop

    def _run(self) -> None:
        while not self._stop.is_set():
            # the single dispatcher must never die of one bad ring or
            # one buggy frame handler: a silent dispatcher death wedges
            # EVERY wire client while the service looks healthy — the
            # same round-level containment the worker loop has
            try:
                busy = self._one_pass()
            except Exception:           # noqa: BLE001 — logged, loud
                self.log.exception(
                    "wire dispatcher pass failed — continuing (a "
                    "repeating error here means a corrupt ring; close "
                    "the offending client)")
                busy = False
            if not busy:
                time.sleep(self.poll_s)

    def _one_pass(self) -> bool:
        busy = self._drain_ctl()
        now = time.monotonic()
        for conn in list(self._conns.values()):
            try:
                busy |= self._drain_client(conn)
                busy |= self._pump_results(conn)
            except OSError as e:
                # a corrupt/oversized record on THIS connection's ring
                # (recv_bytes raises): the connection is unrecoverable
                # — misframed forever — but the server is not
                self.log.error("wire: ring error on %s (%s) — "
                               "declaring the client gone", conn.cid, e)
                self._client_gone(conn, f"ring error: {e}")
            if not conn.dead \
                    and now - conn.last_seen > self.client_lease_s:
                self._client_gone(
                    conn, f"client lease ({self.client_lease_s:g} s)"
                          " missed — client died or wedged")
            if conn.dead and not conn.pending:
                self._close_conn(conn)
        return busy

    def _decode(self, raw: bytes, where: str):
        """Codec-framed decode with CRC rejection: a corrupt frame is
        counted + logged and the connection moves on — a bad frame must
        never be partially applied or kill the dispatcher."""
        try:
            payload, man = ckptlib.loads(raw, where)
        except ckptlib.CheckpointError as e:
            self.svc.telemetry.counter("wire_crc_rejected_total").inc()
            self.log.error("wire: REJECTED corrupt frame on %s: %s",
                           where, e)
            return None
        if man.get("wire_version") != WIRE_VERSION:
            self.svc.telemetry.counter("wire_version_rejected_total").inc()
            self.log.error(
                "wire: REJECTED frame on %s: wire_version %r != %d",
                where, man.get("wire_version"), WIRE_VERSION)
            return None
        return payload, man

    def _drain_ctl(self) -> bool:
        busy = False
        while True:
            raw = self._ctl.recv_bytes()
            if raw is None:
                return busy
            busy = True
            dec = self._decode(raw, self._ctl.name)
            if dec is None:
                continue
            payload, man = dec
            if man.get("kind") != K_HELLO:
                self.log.warning("wire: non-hello frame kind %r on the "
                                 "control ring — ignored", man.get("kind"))
                continue
            cid = str(payload.get("client", ""))
            if not cid or cid in self._conns:
                self.log.warning("wire: bad/duplicate hello %r", cid)
                continue
            try:
                c2s = transport.open_when_ready(f"{self.base}.{cid}.c2s")
                s2c = transport.open_when_ready(f"{self.base}.{cid}.s2c")
            except OSError as e:
                self.log.error("wire: hello from %r but its rings never "
                               "appeared: %s", cid, e)
                continue
            conn = _Conn(cid, c2s, s2c)
            self._conns[cid] = conn
            _send(conn.s2c, _frame(K_HELLO_ACK, {
                "server": self.base,
                "workers": int(self.svc.stats.get("workers", 1))}),
                log=self.log, what="hello-ack")
            self.log.info("wire: client %s connected", cid)

    def _drain_client(self, conn: _Conn) -> bool:
        busy = False
        while not conn.dead:
            raw = conn.c2s.recv_bytes()
            if raw is None:
                return busy
            busy = True
            conn.last_seen = time.monotonic()
            dec = self._decode(raw, conn.c2s.name)
            if dec is None:
                # CRC-rejected: tell the client something arrived broken
                _send(conn.s2c, _frame(K_ERROR, {
                    "error": "corrupt frame rejected (CRC)"}),
                    log=self.log, what="crc-error")
                continue
            payload, man = dec
            kind = man.get("kind")
            if kind == K_PING:
                continue
            if kind == K_BYE:
                self._client_gone(conn, "clean BYE", clean=True)
                return True
            if kind == K_SUBMIT:
                self._handle_submit(conn, payload)
            else:
                self.log.warning("wire: unknown frame kind %r from %s",
                                 kind, conn.cid)
        return busy

    def _handle_submit(self, conn: _Conn, payload: dict) -> None:
        rid = str(payload.get("request_id") or uuid.uuid4().hex[:12])
        # the client frame always carries the key (None when the caller
        # set no deadline), so the connection default applies on None,
        # not on key absence — otherwise it would be dead code
        deadline_s = payload.get("deadline_s")
        if deadline_s is None:
            deadline_s = self.default_deadline_s
        try:
            # the trace starts at the CLIENT: its minted id crosses the
            # wire in the submit frame and the service adopts it, so
            # one trace_id names the request from the external process
            # through admission, chunks, failover, and the result frame
            ticket = self.svc.submit(
                str(payload["kind"]), payload.get("params") or {},
                tenant=str(payload.get("tenant", conn.cid)),
                request_id=rid, deadline_s=deadline_s,
                trace_id=str(payload.get("trace_id") or "") or None)
        except RejectedError as e:
            _send(conn.s2c, _frame(K_REJECT, {
                "request_id": rid, "reason": str(e),
                "retry_after_s": e.retry_after_s}),
                log=self.log, what="reject")
            return
        except (ValueError, KeyError) as e:
            _send(conn.s2c, _frame(K_ERROR, {
                "request_id": rid,
                "error": f"{type(e).__name__}: {e}"}),
                log=self.log, what="refusal")
            return
        conn.pending[rid] = ticket
        _send(conn.s2c, _frame(K_ACCEPT, {"request_id": rid}),
              log=self.log, what="accept")

    def _pump_results(self, conn: _Conn) -> bool:
        """Forward buffered chunk events and terminal results. Runs for
        dead connections too (a batch in flight when the client died
        still terminates — results are discarded at the journal, not
        the scheduler), but skips the sends."""
        busy = False
        for rid in list(conn.pending):
            ticket = conn.pending[rid]
            # capture done BEFORE draining: events always precede the
            # resolution, so everything pushed before a True here is in
            # the queue we are about to drain. Capturing after would
            # race a resolve landing mid-drain and drop the trailing
            # chunk event(s) when the rid is retired below.
            done_now = ticket.done
            while True:
                try:
                    ev = ticket._events.get_nowait()
                except queuelib.Empty:
                    break
                if ev is _TICKET_SENTINEL:
                    ticket._events.put(_TICKET_SENTINEL)   # keep sticky
                    break
                busy = True
                if not conn.dead and isinstance(ev, ChunkEvent):
                    _send(conn.s2c, _frame(K_EVENT, {
                        "request_id": rid, "seq": ev.seq,
                        "payload": dict(ev.payload)}),
                        log=self.log, what="event")
            if done_now:
                busy = True
                res = ticket.result(timeout=0)
                if not conn.dead:
                    _send(conn.s2c, _frame(K_RESULT, {
                        "request_id": rid, "status": res.status,
                        "value": res.value,
                        "error": res.error.to_row() if res.error
                        else None,
                        "latency_s": res.latency_s,
                        "queued_s": res.queued_s,
                        "chunks": res.chunks,
                        "preemptions": res.preemptions,
                        "resumed": res.resumed,
                        "failovers": res.failovers,
                        "trace_id": res.trace_id}),
                        log=self.log, what="result")
                del conn.pending[rid]
        return busy

    def _client_gone(self, conn: _Conn, reason: str,
                     clean: bool = False) -> None:
        """Loud disconnect: cancel the dead client's entries with a
        structured ``cancelled`` error — queued ones immediately,
        resident ones at their next chunk boundary — never the running
        batch mid-kernel. Every ticket stays registered so
        `_pump_results` retires it when its terminal (cancelled or
        completed-and-discarded) result lands."""
        conn.dead = True
        outcome = {rid: self.svc.cancel(
            rid, f"wire client {conn.cid} gone ({reason})")
            for rid in list(conn.pending)}
        queued = sum(1 for o in outcome.values() if o == "queued")
        resident = sum(1 for o in outcome.values() if o == "resident")
        terminal = len(outcome) - queued - resident
        (self.log.info if clean else self.log.error)(
            "wire: client %s disconnected (%s) — %d queued entr%s "
            "cancelled now, %d resident request(s) cancelled at their "
            "next chunk boundary, %d already terminal; results are "
            "discarded", conn.cid, reason, queued,
            "y" if queued == 1 else "ies", resident, terminal)
        self.svc.telemetry.counter("wire_client_disconnects_total").inc()

    def _close_conn(self, conn: _Conn) -> None:
        self._conns.pop(conn.cid, None)
        # the CLIENT owns its rings; the server only unmaps
        conn.c2s.close(unlink=False)
        conn.s2c.close(unlink=False)

    def close(self) -> None:
        self._stop.set()
        self._thread.join(10.0)
        for conn in list(self._conns.values()):
            if not conn.dead:
                _send(conn.s2c, _frame(K_ERROR, {
                    "error": f"{E_SHUTDOWN}: wire server closing"}),
                    grace_s=0.2)
            self._close_conn(conn)
        self._ctl.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class WireClient:
    """External-process client: submit requests over the shm rings and
    hold ordinary `Ticket`s — the same per-chunk stream + terminal
    `Result` surface the in-process API gives, resolved by a background
    reader thread. A rejected submit resolves the ticket with the same
    structured ``queue_full`` failure `submit_and_wait` produces."""

    def __init__(self, base: str = "aclswarm-serve",
                 client_id: Optional[str] = None, *,
                 tenant: Optional[str] = None,
                 hello_timeout_s: float = 10.0,
                 ping_s: float = 2.0, log=None):
        self.base = base
        self.cid = client_id or uuid.uuid4().hex[:8]
        self.tenant = tenant or self.cid
        self.ping_s = float(ping_s)
        self.log = log or get_logger("serve.wire.client")
        # the client OWNS its connection rings; the server opens them
        # after the hello
        self._c2s = transport.Channel(f"{base}.{self.cid}.c2s",
                                      create=True,
                                      capacity=RING_CAPACITY)
        self._s2c = transport.Channel(f"{base}.{self.cid}.s2c",
                                      create=True,
                                      capacity=RING_CAPACITY)
        self._ctl = transport.open_when_ready(f"{base}.ctl",
                                              grace_s=hello_timeout_s)
        self._tickets: dict[str, Ticket] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._connected = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"wire-client-{self.cid}")
        self._thread.start()
        # the ctl ring is shared by every connecting client but the shm
        # ring is single-producer: serialize the hello behind the
        # cross-process writer lock
        with _ctl_writer_lock(base):
            sent = _send(self._ctl, _frame(K_HELLO, {"client": self.cid}),
                         grace_s=hello_timeout_s, log=self.log,
                         what="hello")
        if not sent:
            self.close()
            raise OSError(f"wire hello to {base}.ctl not accepted within "
                          f"{hello_timeout_s:g} s (no server draining?)")
        if not self._connected.wait(hello_timeout_s):
            self.close()
            raise OSError(f"wire server on {base!r} never acked the "
                          f"hello within {hello_timeout_s:g} s")

    # -------------------------------------------------------------- API

    def submit(self, kind: str, params: dict, *,
               request_id: Optional[str] = None,
               tenant: Optional[str] = None,
               deadline_s: Optional[float] = None,
               trace_id: Optional[str] = None) -> Ticket:
        rid = request_id or uuid.uuid4().hex[:12]
        with self._lock:
            if rid in self._tickets:
                return self._tickets[rid]
            ticket = Ticket(rid)
            self._tickets[rid] = ticket
        # swarmtrace: the trace is minted HERE, at the true origin —
        # the server adopts it, so the off-process hop is inside the
        # traced window instead of invisible before it
        ok = _send(self._c2s, _frame(K_SUBMIT, {
            "request_id": rid, "kind": kind, "params": params,
            "tenant": tenant or self.tenant, "deadline_s": deadline_s,
            "trace_id": trace_id or mint_trace_id()}),
            log=self.log, what=f"submit {rid}")
        if not ok:
            ticket._resolve(Result(
                request_id=rid, status=FAILED,
                error=ServeError(E_SHUTDOWN,
                                 "wire submit never left the ring "
                                 "(server not draining)")))
        return ticket

    def submit_and_wait(self, kind: str, params: dict, *,
                        timeout: Optional[float] = None,
                        **kw) -> Result:
        return self.submit(kind, params, **kw).result(timeout=timeout)

    # ------------------------------------------------------------- loop

    def _run(self) -> None:
        last_ping = time.monotonic()
        while not self._stop.is_set():
            raw = self._s2c.recv_bytes()
            now = time.monotonic()
            if now - last_ping >= self.ping_s:
                # liveness: the server cancels queued entries of a
                # client whose lease lapses — pings keep it alive while
                # this process waits on long results
                self._c2s.send_bytes(_frame(K_PING, {}))
                last_ping = now
            if raw is None:
                time.sleep(0.002)
                continue
            try:
                payload, man = ckptlib.loads(raw, self._s2c.name)
            except ckptlib.CheckpointError as e:
                self.log.error("wire client: corrupt server frame: %s", e)
                continue
            self._handle(payload, man.get("kind"))

    def _handle(self, payload: dict, kind: Optional[str]) -> None:
        if kind == K_HELLO_ACK:
            self._connected.set()
            return
        rid = str(payload.get("request_id", ""))
        ticket = self._tickets.get(rid)
        if kind == K_EVENT and ticket is not None:
            ticket._push(ChunkEvent(rid, int(payload.get("seq", 0)),
                                    dict(payload.get("payload") or {})))
        elif kind == K_RESULT and ticket is not None:
            err = payload.get("error")
            ticket._resolve(Result(
                request_id=rid, status=str(payload["status"]),
                value=payload.get("value"),
                error=ServeError(**err) if err else None,
                latency_s=float(payload.get("latency_s", 0.0)),
                queued_s=float(payload.get("queued_s", 0.0)),
                chunks=int(payload.get("chunks", 0)),
                preemptions=int(payload.get("preemptions", 0)),
                resumed=bool(payload.get("resumed", False)),
                failovers=int(payload.get("failovers", 0)),
                trace_id=str(payload.get("trace_id", ""))))
        elif kind == K_REJECT and ticket is not None:
            ticket._resolve(Result(
                request_id=rid, status=FAILED,
                error=ServeError(
                    E_QUEUE_FULL, str(payload.get("reason", "rejected")),
                    detail={"retry_after_s":
                            float(payload.get("retry_after_s", 0.0))})))
        elif kind == K_ERROR:
            msg = str(payload.get("error", "server error"))
            if ticket is not None:
                ticket._resolve(Result(
                    request_id=rid, status=FAILED,
                    error=ServeError("wire_error", msg)))
            else:
                self.log.error("wire client: server error: %s", msg)
        elif kind == K_ACCEPT:
            pass                     # the ticket already exists
        else:
            self.log.warning("wire client: unknown frame kind %r", kind)

    def close(self, bye: bool = True) -> None:
        """Clean shutdown: BYE tells the server to cancel anything
        still queued for this client (loudly, with structured errors)
        instead of waiting out the lease."""
        if bye:
            try:
                self._c2s.send_bytes(_frame(K_BYE, {}))
            except Exception:        # noqa: BLE001 — ring may be gone
                pass
        self._stop.set()
        self._thread.join(5.0)
        self._ctl.close()
        self._c2s.close()
        self._s2c.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
