"""swarmserve — the hardened always-on serving layer (docs/SERVICE.md).

ROADMAP open item 2 made concrete: a persistent in-process service over
the batched rollout engine. A threaded queue front end accepts
heterogeneous rollout / assignment / gain-design requests from many
tenants, packs compatible work into shape-bucketed, power-of-two,
continuously refilled device batches, and streams per-chunk results
back per request. The robustness contract is the product:

- **admission control + backpressure** — bounded per-tenant and global
  queues; overload is an explicit `RejectedError` with a drain-rate
  ``retry_after_s`` hint, never unbounded growth (`serve.admission`);
- **zero silent losses** — accepted requests are journaled durably
  before `submit` returns and ALWAYS terminate with a value or a
  structured `ServeError`, across deadline expiry, preemption, and
  worker SIGKILL + recovery (`serve.service`, proven by `serve.smoke`
  and `benchmarks/serve_soak.py`);
- **deadline enforcement at chunk boundaries** — timed-out work is
  cancelled with a structured ``deadline_exceeded`` error, not a hang;
- **per-tenant fair scheduling** — round-robin batch slots; a flooding
  tenant cannot starve the others;
- **checkpoint-backed preemption** — long rollouts past their quantum
  are evicted through the PR-5 checkpoint codec and resume
  bit-identically (eviction is free);
- **degraded-mode operation** — transient device failures retry and
  fall back to CPU with loud markers (`resilience.ChunkExecutor`);
- **device-bound rounds** — requests are prepped into batch-layout rows
  at submit, rounds run off donated per-bucket staging buffers with
  double-buffered chunk pipelining, and each round's host sync is one
  `device_get` of a compacted result pytree (`serve.staging`).

The engine entry points are the same jitted programs the trial drivers
use (their HLO baseline is unchanged); `serve.staging` adds six small
audited entry points of its own (write_row / gather_rows /
scatter_rows / take_row / unpack_round / init_row — see
`analysis.trace_audit`).
"""
from aclswarm_tpu.serve.api import (COMPLETED, FAILED, PREEMPTED, QUEUED,
                                    RUNNING, TERMINAL, TIMED_OUT,
                                    ChunkEvent, RejectedError, Request,
                                    Result, ServeError, Ticket)
from aclswarm_tpu.serve.client import probe_backend, submit_and_wait
from aclswarm_tpu.serve.service import (BUILTIN_KINDS, ServiceConfig,
                                        SwarmService, bucket_of)
from aclswarm_tpu.serve.stats import ServeStats
from aclswarm_tpu.serve.workers import WorkerPool, place_slot

__all__ = [
    "COMPLETED", "FAILED", "PREEMPTED", "QUEUED", "RUNNING", "TERMINAL",
    "TIMED_OUT", "ChunkEvent", "RejectedError", "Request", "Result",
    "ServeError", "Ticket", "probe_backend", "submit_and_wait",
    "BUILTIN_KINDS", "ServiceConfig", "SwarmService", "ServeStats",
    "WorkerPool", "bucket_of", "place_slot",
]
# WireServer / WireClient live in `aclswarm_tpu.serve.wire` and are
# imported from there directly: the shm transport requires the native
# library (make -C native), which must stay optional for the core
# service. The TCP binding (`WireServer(tcp=...)`) and the traffic
# fleet (`aclswarm_tpu.serve.traffic`) are pure stdlib.
