"""swarmserve request/response surface (docs/SERVICE.md).

Everything a client touches lives here: the request record, the status
and error vocabulary, the streaming `Ticket` handle, and the terminal
`Result`. The contract the whole layer is built around:

    **every ACCEPTED request terminates with a `Result` carrying either
    a value or a structured `ServeError` — never a silent loss, never a
    hang.**

Acceptance is the dividing line. A `submit` that raises
`RejectedError` was *refused* (bounded queue, shutdown) — the client
holds the backpressure hint (`retry_after_s`) and nothing was promised.
A `submit` that returns a `Ticket` was *accepted*: from that moment the
service owes a terminal result, across preemption, worker SIGKILL, and
deadline expiry (the failure-semantics table in docs/SERVICE.md names
what the client sees for each fault class).

Request ``params`` must be checkpoint-codec-serializable (dicts, lists,
scalars, numpy arrays — `resilience.checkpoint`): an accepted request is
journaled durably before `submit` returns, which is what makes the
zero-silent-loss promise survive a killed worker process.
"""
from __future__ import annotations

import dataclasses
import queue
import threading
from typing import Any, Iterator, Optional

# -- request lifecycle states ------------------------------------------------
QUEUED = "queued"          # accepted, waiting for a batch slot
RUNNING = "running"        # resident in the device batch
PREEMPTED = "preempted"    # evicted to checkpoint; will be rescheduled
COMPLETED = "completed"    # terminal: value delivered
FAILED = "failed"          # terminal: structured execution error
TIMED_OUT = "timed_out"    # terminal: deadline enforced at a boundary
TERMINAL = (COMPLETED, FAILED, TIMED_OUT)

# -- structured error codes (the failure-semantics table) --------------------
E_DEADLINE = "deadline_exceeded"   # deadline passed at a chunk boundary
E_EXECUTION = "execution_failed"   # retries + fallback exhausted, or a bug
E_SHUTDOWN = "service_shutdown"    # non-drain close with work still queued
E_QUEUE_FULL = "queue_full"        # RejectedError.reason (never a Result)
E_POISONED = "poisoned"            # request killed K distinct workers —
#                                    excluded everywhere, terminated
#                                    instead of ping-ponging the fleet
E_CANCELLED = "cancelled"          # cancelled before/at a boundary (wire
#                                    client death cancels its queue
#                                    entries — never the running batch)
# client-side codes (`serve.client` — never journaled; the service
# still owes the result when these are reported):
E_CLIENT_TIMEOUT = "client_timeout"   # the CLIENT stopped waiting
E_WORKER_DIED = "worker_died"         # worker dead with the ticket open


class RejectedError(RuntimeError):
    """Admission refused this submit — the bounded-queue backpressure
    signal. The request was NOT accepted (nothing journaled, nothing
    owed); ``retry_after_s`` is the service's drain estimate."""

    def __init__(self, reason: str, retry_after_s: float):
        self.reason = reason
        self.retry_after_s = float(retry_after_s)
        super().__init__(f"request rejected ({reason}); retry after "
                         f"~{self.retry_after_s:.2f} s")


@dataclasses.dataclass
class ServeError:
    """The structured error a terminal `Result` carries instead of a
    value. ``code`` is one of the ``E_*`` constants; ``detail`` is
    free-form evidence (e.g. the `ExecutionFailure` rows of a failed
    stage) — codec-serializable so it survives the journal."""

    code: str
    message: str
    detail: Optional[dict] = None

    def to_row(self) -> dict:
        row: dict = {"code": self.code, "message": self.message}
        if self.detail is not None:
            row["detail"] = self.detail
        return row


@dataclasses.dataclass
class Request:
    """One unit of admitted work. ``deadline_s`` is relative to
    acceptance (``t_submit``, wall clock — it must survive a process
    restart, so no monotonic clocks here)."""

    kind: str                 # 'rollout' | 'assign' | 'gains' | 'stats'
    #                           | 'scenario' (a registry-drawn rollout —
    #                           batches WITH plain rollouts) | registered
    params: dict
    tenant: str = "default"
    request_id: str = ""
    deadline_s: Optional[float] = None
    t_submit: float = 0.0     # wall-clock acceptance time (service-set)
    trace_id: str = ""        # swarmtrace causal id: minted at submit
    #                           (wire client or direct API) and carried
    #                           through journal frames, checkpoint
    #                           manifests, and every lifecycle event

    @property
    def t_deadline(self) -> Optional[float]:
        if self.deadline_s is None:
            return None
        return self.t_submit + self.deadline_s


@dataclasses.dataclass
class ChunkEvent:
    """One streamed progress record: the serve analogue of the trial
    drivers' per-chunk host sync. ``payload`` carries the chunk index,
    the end tick, and a running bit-exact digest of the positions."""

    request_id: str
    seq: int
    payload: dict


@dataclasses.dataclass
class Result:
    """The terminal record (also what the journal's done-frame stores).
    Exactly one of ``value`` / ``error`` is set, keyed by ``status``."""

    request_id: str
    status: str                      # COMPLETED | FAILED | TIMED_OUT
    value: Any = None
    error: Optional[ServeError] = None
    latency_s: float = 0.0           # accept -> terminal (wall clock)
    queued_s: float = 0.0            # accept -> first scheduled
    chunks: int = 0                  # device chunks executed
    preemptions: int = 0             # checkpoint-backed evictions survived
    resumed: bool = False            # continued from a journaled checkpoint
    failovers: int = 0               # worker-death migrations survived
    #                                  (checkpoint-backed, bit-identical)
    trace_id: str = ""               # the request's swarmtrace id — the
    #                                  key `telemetry.postmortem` joins
    #                                  the journal timeline on

    @property
    def ok(self) -> bool:
        return self.status == COMPLETED


_SENTINEL = object()


class Ticket:
    """Client handle for one accepted request: stream per-chunk events
    as they land, block for the terminal result. Thread-safe — the
    worker resolves it, any number of client threads may wait."""

    def __init__(self, request_id: str):
        self.request_id = request_id
        self._events: queue.Queue = queue.Queue()
        self._result: Optional[Result] = None
        self._done = threading.Event()
        # guards the resolve once-check: test-and-commit must be one
        # atomic step or two racing resolvers (worker vs recovery vs
        # wire reader) can both pass the check and the LAST writer's
        # result overwrites the first after waiters saw it
        self._resolve_lock = threading.Lock()
        # True once the service has accepted the request. In-process
        # tickets exist only post-acceptance (submit raises otherwise);
        # the wire client flips it False until the accept frame lands,
        # so open-loop clients can tell accepted-and-running from
        # still-awaiting-a-verdict without blocking on the result.
        self.accepted = True

    # -- service side ------------------------------------------------------
    def _push(self, event: ChunkEvent) -> None:
        self._events.put(event)

    def _resolve(self, result: Result) -> None:
        """Terminal: publish the result and close the event stream.
        First resolution wins (idempotent — recovery paths may race):
        the once-check and the commit share `_resolve_lock`, so the
        loser of a race observes the winner's publication instead of
        overwriting it."""
        with self._resolve_lock:
            if self._done.is_set():
                return
            self._result = result
            self._done.set()
        self._events.put(_SENTINEL)

    # -- client side -------------------------------------------------------
    @property
    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None) -> Result:
        """Block for the terminal `Result` (value OR structured error —
        a timeout here means the CLIENT gave up waiting, not that the
        service dropped the request)."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request {self.request_id} not terminal within "
                f"{timeout} s (still owed by the service)")
        assert self._result is not None
        return self._result

    def stream(self, timeout: Optional[float] = None
               ) -> Iterator[ChunkEvent]:
        """Yield `ChunkEvent`s until the request resolves. ``timeout``
        bounds the wait per event: lapsing raises `TimeoutError` (not
        the queue module's internal exception). Events are consumed
        once, but the end-of-stream marker is sticky — a later
        `stream()` on a resolved ticket terminates instead of blocking
        forever."""
        while True:
            try:
                ev = self._events.get(timeout=timeout)
            except queue.Empty:
                raise TimeoutError(
                    f"no chunk event for request {self.request_id} "
                    f"within {timeout} s") from None
            if ev is _SENTINEL:
                # re-arm the sentinel for any other/later stream()
                self._events.put(_SENTINEL)
                return
            yield ev
