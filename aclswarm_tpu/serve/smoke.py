"""serve smoke: SIGKILL the serving worker mid-batch, recover, prove
zero losses + bit-identical resume (docs/SERVICE.md; run by
`scripts/check.sh`).

The end-to-end shape of the promise, in under a minute on CPU:

1. a CHILD process starts a journaled `SwarmService`, submits 3 mixed
   requests (a faulted rollout, an assignment, a gain design), and is
   ``SIGKILL``ed by the env-armed `CrashPlan` at serve round boundary 2
   — mid-batch, with the rollout partially done and checkpointed;
2. the parent verifies the child died by signal, then starts a SECOND
   child on the SAME journal: recovery re-admits every accepted-but-
   unfinished request (resuming the rollout from its checkpoint) and
   drains to idle;
3. the parent asserts every accepted request has a terminal done-frame
   (zero silent losses) and that the resumed rollout's final digest is
   BIT-IDENTICAL to an uninterrupted in-parent run.

    JAX_PLATFORMS=cpu python -m aclswarm_tpu.serve.smoke

``--multiworker`` runs the WORKER-crash half of the story instead
(docs/SERVICE.md §multi-worker): a 2-worker journaled service, the
worker owning the rollout bucket is killed mid-batch by a
worker-targeted `CrashPlan`, and the supervisor fails the orphaned
rollout over to the surviving worker THROUGH the checkpoint codec —
zero losses, the migrated resume bit-identical to an uncontended run,
and the service never stops serving (the kill is a failover, not an
outage). `scripts/check.sh` runs both modes.

    JAX_PLATFORMS=cpu python -m aclswarm_tpu.serve.smoke --multiworker

``--postmortem`` is the swarmtrace drill (docs/OBSERVABILITY.md
§swarmtrace): a 2-worker journaled service, the worker owning the
rollout bucket killed mid-flight, and then — from the ON-DISK journal
alone — `telemetry.postmortem` must reconstruct the migrated request's
causally-ordered timeline: complete (submitted → resolved), gap-free
chunk coverage across the kill, one trace_id on every record, a
non-zero failover gap in the per-stage breakdown, and the span ring
flushed by the supervisor on the worker's behalf.

    JAX_PLATFORMS=cpu python -m aclswarm_tpu.serve.smoke --postmortem
"""
from __future__ import annotations

import argparse
import contextlib
import json
import os
import signal
import subprocess
import sys
import tempfile
import time
from pathlib import Path

from aclswarm_tpu.resilience import checkpoint as ckptlib
from aclswarm_tpu.resilience.crash import ENV_VAR, CrashPlan, arm
from aclswarm_tpu.serve import (ServiceConfig, SwarmService, bucket_of,
                                place_slot)
from aclswarm_tpu.serve.service import _read_frame

# On the pipelined schedule (PR 11: `_round_start` dispatches round
# k+1 before round k resolves), round 1 dispatches the rollout's chunk
# 1, round 2 runs a single-shot while chunk 1 resolves + checkpoints,
# and round 3 re-picks the rollout — killing at 3 lands with exactly
# one chunk durable and the next mid-flight, the same shape the old
# round-2 kill produced on the sequential schedule.
KILL_ROUND = 3

REQUESTS = [
    {"kind": "rollout", "tenant": "a", "request_id": "smoke-roll",
     "params": {"n": 5, "ticks": 80, "chunk_ticks": 20, "seed": 11,
                "faults": {"dropout_frac": 0.4, "drop_tick": 15,
                           "rejoin_tick": 45}}},
    {"kind": "assign", "tenant": "b", "request_id": "smoke-assign",
     "params": {"n": 12, "seed": 3}},
    {"kind": "gains", "tenant": "c", "request_id": "smoke-gains",
     "params": {"n": 5, "seed": 0}},
]


@contextlib.contextmanager
def _journal_dir(tag: str):
    """The smoke's journal directory. Ephemeral by default; when
    ``ACLSWARM_KEEP_JOURNALS`` names a directory, the journal survives
    the run under ``$ACLSWARM_KEEP_JOURNALS/<tag>/`` — the refinement
    gate (`analysis.model --refine`, scripts/check.sh) replays exactly
    the crash-drill journals the smokes already produce, at zero extra
    smoke runtime."""
    keep = os.environ.get("ACLSWARM_KEEP_JOURNALS")
    if keep:
        d = Path(keep) / tag
        d.mkdir(parents=True, exist_ok=True)
        yield str(d)
        return
    with tempfile.TemporaryDirectory(
            prefix=f"aclswarm_{tag}_smoke_") as d:
        yield d


def _service(journal: str) -> SwarmService:
    # max_batch=1 serializes the rounds so the kill boundary is
    # deterministic: round 1 runs the rollout's first chunk, and the
    # KILL_ROUND kill arrives with a batch picked and work un-journaled
    return SwarmService(ServiceConfig(max_batch=1, quantum_chunks=1,
                                      journal_dir=journal))


def child(journal: str) -> int:
    svc = _service(journal)
    tickets = [svc.submit(r["kind"], r["params"], tenant=r["tenant"],
                          request_id=r["request_id"]) for r in REQUESTS]
    # armed: the SIGKILL lands inside the worker loop; this wait never
    # finishes in run 1 and drains cleanly in run 2
    for t in tickets:
        t.result(timeout=300)
    svc.close()
    print("child: all requests terminal")
    return 0


def run_smoke() -> int:
    with _journal_dir("serve") as d:
        env = dict(os.environ, **{ENV_VAR: f"serve:{KILL_ROUND}:kill"})
        t0 = time.time()
        r = subprocess.run(
            [sys.executable, "-m", "aclswarm_tpu.serve.smoke",
             "--child", "--dir", d],
            env=env, capture_output=True, text=True, timeout=600)
        if r.returncode != -signal.SIGKILL:
            print(f"FAIL: child exited {r.returncode}, expected "
                  f"{-signal.SIGKILL} (SIGKILL)\n{r.stdout}\n{r.stderr}")
            return 1
        accepted = sorted(p.name for p in Path(d).glob("req_*.req"))
        if len(accepted) != len(REQUESTS):
            print(f"FAIL: journal lost acceptances: {accepted}")
            return 1
        print(f"worker SIGKILL'd at serve round {KILL_ROUND} after "
              f"{time.time() - t0:.1f}s; journal: {len(accepted)} "
              "accepted requests survive")

        env2 = dict(os.environ)
        env2.pop(ENV_VAR, None)
        r2 = subprocess.run(
            [sys.executable, "-m", "aclswarm_tpu.serve.smoke",
             "--child", "--dir", d],
            env=env2, capture_output=True, text=True, timeout=600)
        if r2.returncode != 0:
            print(f"FAIL: recovery child exited {r2.returncode}\n"
                  f"{r2.stdout}\n{r2.stderr}")
            return 1

        # zero silent losses: every accepted request is terminal
        ledger = {}
        for reqf in Path(d).glob("req_*.req"):
            rid = reqf.name[len("req_"):-len(".req")]
            donef = reqf.with_suffix(".done")
            if not donef.exists():
                print(f"FAIL: request {rid} accepted but never terminal "
                      "(SILENT LOSS)")
                return 1
            _, man = _read_frame(donef)
            ledger[rid] = man
        statuses = {k: v["status"] for k, v in ledger.items()}
        print(f"ledger: {json.dumps(statuses, sort_keys=True)}")
        if set(statuses.values()) != {"completed"}:
            print("FAIL: expected every smoke request to complete")
            return 1
        if not ledger["smoke-roll"].get("resumed"):
            print("FAIL: rollout did not resume from its checkpoint")
            return 1

        # bit-identical resume: uninterrupted reference run in-parent
        payload, _ = _read_frame(
            Path(d) / "req_smoke-roll.done")
        resumed_digest = payload["value"]["digest"]
        ref = SwarmService(ServiceConfig(max_batch=1))
        spec = REQUESTS[0]
        ref_res = ref.submit(spec["kind"], spec["params"]).result(300)
        ref.close()
        if ref_res.value["digest"] != resumed_digest:
            print(f"FAIL: resumed digest {resumed_digest:#x} != "
                  f"uninterrupted {ref_res.value['digest']:#x}")
            return 1
    print("PASS: SIGKILL mid-batch lost nothing — 3/3 accepted requests "
          "terminal after recovery, resumed rollout bit-identical "
          f"(digest {resumed_digest:#010x})")
    return 0


def run_multiworker() -> int:
    """The worker-crash failover drill: SIGKILL one of two workers
    (thread-abrupt death — the in-process analogue of a worker process
    SIGKILL: no cleanup, in-flight work orphaned), assert zero loss +
    a bit-identical cross-worker migrated resume."""
    t0 = time.time()
    roll = REQUESTS[0]["params"]
    # the bit-parity oracle: an uncontended single-worker run
    ref = SwarmService(ServiceConfig(max_batch=1))
    want = ref.submit("rollout", roll).result(300)
    ref.close()
    assert want.ok

    with _journal_dir("mw") as d:
        # swarmwatch rides the drill (docs/OBSERVABILITY.md §swarmwatch):
        # the kill below must surface on the LIVE health surface, not
        # just in the postmortem journal. Rejoin backoff > sampler
        # interval so the dead slot's gauge is sampled down at least
        # once before the respawn flips it back.
        svc = SwarmService(ServiceConfig(
            workers=2, max_batch=1, quantum_chunks=8, journal_dir=d,
            supervise_poll_s=0.02, rejoin_base_s=0.3,
            watch=True, watch_interval_s=0.05))
        # kill the worker that OWNS the rollout bucket, at its round 2:
        # one chunk done + checkpointed, the next mid-flight. The
        # rollout goes in ALONE so the victim's round schedule is
        # deterministic (chunk 1 = round 1, chunk 2 = round 2); the
        # single-shot requests follow once the kill has landed,
        # proving the degraded fleet keeps serving THROUGH a failover.
        slot = place_slot(bucket_of("rollout", roll), [0, 1])
        arm(CrashPlan(f"serve.w{slot}", 2, "raise"))
        tickets = [svc.submit(REQUESTS[0]["kind"], REQUESTS[0]["params"],
                              tenant=REQUESTS[0]["tenant"],
                              request_id=REQUESTS[0]["request_id"])]
        deadline = time.monotonic() + 120
        while svc.stats["failovers"] < 1 \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        tickets += [svc.submit(r["kind"], r["params"], tenant=r["tenant"],
                               request_id=r["request_id"])
                    for r in REQUESTS[1:]]
        results = {r["request_id"]: t.result(timeout=300)
                   for r, t in zip(REQUESTS, tickets)}
        arm(None)
        stats = dict(svc.stats)
        alive_through = svc.alive
        # the swarmwatch half of the drill: scrape the `health` kind
        # (the same request surface a WireClient scrapes over TCP) and
        # assert the kill is VISIBLE — a worker_up alert fired
        health = svc.submit("health", {}, tenant="ops").result(60)
        svc.close()
        if not health.ok or not health.value.get("watch_enabled"):
            print(f"FAIL: health scrape unusable: {health.status}")
            return 1
        wu = (health.value.get("watch") or {}).get(
            "verdicts", {}).get("worker_up", {})
        if int(wu.get("fired", 0)) < 1:
            print("FAIL: the worker kill never fired a worker_up alert "
                  f"on the live health surface (verdict: {wu})")
            return 1
        from aclswarm_tpu.telemetry.lifecycle import LifecycleLog
        alert_rows, _ = LifecycleLog.read(Path(d) / "events.log")
        alert_fired = any(
            r.get("event") == "alert" and r.get("slo") == "worker_up"
            and r.get("state") == "firing" for r in alert_rows)
        if not alert_fired:
            print("FAIL: no worker_up firing alert record in the "
                  "journal's events.log — the live surface and the "
                  "postmortem stream disagree")
            return 1

        losses = [rid for rid, res in results.items()
                  if res.status not in ("completed",)]
        if losses:
            print(f"FAIL: requests did not complete across the worker "
                  f"kill: {losses}")
            return 1
        roll_res = results["smoke-roll"]
        if roll_res.failovers < 1:
            print("FAIL: the rollout never migrated (failovers="
                  f"{roll_res.failovers}) — the kill missed its worker")
            return 1
        if roll_res.value["digest"] != want.value["digest"]:
            print(f"FAIL: migrated digest {roll_res.value['digest']:#x} "
                  f"!= uncontended {want.value['digest']:#x}")
            return 1
        if stats["failovers"] < 1 or stats["requeued"] < 1:
            print(f"FAIL: failover not recorded in stats: {stats}")
            return 1
        if not alive_through:
            print("FAIL: service reported dead during a routine "
                  "worker failover")
            return 1
    print("PASS: worker kill mid-batch lost nothing — 3/3 requests "
          f"terminal, rollout migrated off worker {slot} after "
          f"{roll_res.failovers} failover(s), resume bit-identical "
          f"(digest {roll_res.value['digest']:#010x}); swarmwatch saw "
          f"the kill live (worker_up fired {int(wu.get('fired', 0))}x "
          "on the health surface + journaled alert record), "
          f"{time.time() - t0:.1f}s")
    return 0


def run_postmortem() -> int:
    """The swarmtrace smoke: kill a worker mid-rollout, then prove the
    migrated request's whole story reconstructs from the journal alone
    — complete, causally ordered, gap-free — with the failover visible
    in the per-stage latency breakdown."""
    from aclswarm_tpu.telemetry import postmortem

    t0 = time.time()
    roll = REQUESTS[0]["params"]
    with _journal_dir("pm") as d:
        svc = SwarmService(ServiceConfig(
            workers=2, max_batch=1, quantum_chunks=8, journal_dir=d,
            supervise_poll_s=0.02, rejoin_base_s=0.05))
        slot = place_slot(bucket_of("rollout", roll), [0, 1])
        arm(CrashPlan(f"serve.w{slot}", 2, "raise"))
        res = svc.submit("rollout", roll, tenant="a",
                         request_id="pm-roll").result(timeout=300)
        arm(None)
        svc.close()
        if not res.ok or res.failovers < 1:
            print(f"FAIL: expected a migrated completion, got "
                  f"{res.status} (failovers={res.failovers})")
            return 1

        # reconstruction from DISK alone — the service object above is
        # deliberately not consulted
        report = postmortem.reconstruct(d)
        rep = report["requests"].get("pm-roll")
        if rep is None:
            print("FAIL: postmortem found no timeline for pm-roll")
            return 1
        problems = []
        if not rep["complete"]:
            problems.append("timeline incomplete")
        if not rep["gap_free"]:
            problems.append(f"timeline not gap-free: {rep['problems']}")
        if rep["migrations"] < 1:
            problems.append("no migrated event in the timeline")
        if rep["trace_id"] != res.trace_id:
            problems.append(
                f"trace_id drift: result {res.trace_id!r} vs journal "
                f"{rep['trace_id']!r}")
        if rep["chunks"] != res.chunks:
            problems.append(f"chunk coverage {rep['chunks']} != "
                            f"result chunks {res.chunks}")
        # the close() dump always writes the file — only a header whose
        # reason names the worker death proves the SUPERVISOR flushed
        # (the path a SIGKILLed worker depends on)
        dumpf = Path(d) / "spans_dump.jsonl"
        headers = []
        if dumpf.is_file():
            headers = [json.loads(ln)
                       for ln in dumpf.read_text().splitlines()
                       if '"span_dump"' in ln]
        if not any("declared dead" in h.get("span_dump", "")
                   for h in headers):
            problems.append("supervisor did not flush the span ring on "
                            "the worker death (no 'declared dead' dump "
                            f"header; saw {[h.get('span_dump') for h in headers]})")
        if problems:
            print("FAIL: " + "; ".join(problems))
            return 1
        st = rep["stages"]
    print("PASS: killed worker %s mid-rollout; postmortem reconstructed "
          "a complete, gap-free timeline from the journal alone — "
          "%d events, %d chunks, %d migration(s), trace %s, stages "
          "queue=%.3fs device=%.3fs failover_gap=%.3fs (%.1fs)"
          % (slot, rep["events"], rep["chunks"], rep["migrations"],
             rep["trace_id"], st["queue_wait_s"], st["device_s"],
             st["failover_gap_s"], time.time() - t0))
    return 0


def run_procs() -> int:
    """The PROCESS-MODE drill (docs/SERVICE.md §process mode): a
    router tier over two procworker OS processes, SIGKILL one with a
    rollout mid-flight, and prove (a) the router's promise survives —
    the client ticket resolves completed with a bit-identical digest,
    (b) zero journaled losses and a gap-free story reconstruct from
    the per-slot journals ALONE (`postmortem.fleet_reconstruct` — the
    killed pid is gone), (c) a graceful rolling restart re-admits a
    NEW incarnation per slot while the fleet keeps serving."""
    from aclswarm_tpu.serve.router import RouterConfig, SwarmRouter
    from aclswarm_tpu.telemetry import postmortem

    t0 = time.time()
    roll = REQUESTS[0]["params"]
    ref = SwarmService(ServiceConfig(max_batch=1))
    want = ref.submit("rollout", roll).result(300)
    ref.close()
    assert want.ok

    with _journal_dir("proc") as d:
        router = SwarmRouter(RouterConfig(
            journal_root=d, slots=2,
            worker={"service": {"max_batch": 1, "quantum_chunks": 1}}))
        router.start()
        if not router.wait_ready(150):
            print(f"FAIL: fleet never came up: {router.fleet()}")
            return 1
        pids0 = {f["slot"]: f["pid"] for f in router.fleet()}
        tickets = [router.submit(r["kind"], r["params"],
                                 tenant=r["tenant"],
                                 request_id=r["request_id"])
                   for r in REQUESTS]
        # kill the PROCESS that owns the rollout once its work is
        # actually in flight there
        deadline = time.monotonic() + 60
        victim = None
        while victim is None and time.monotonic() < deadline:
            for f in router.fleet():
                if router.inflight_on(f["uid"]):
                    victim = f["slot"]
                    break
            time.sleep(0.02)
        if victim is None:
            print("FAIL: nothing ever dispatched")
            return 1
        drill = router.kill_slot(victim)
        results = {r["request_id"]: t.result(timeout=300)
                   for r, t in zip(REQUESTS, tickets)}
        losses = [rid for rid, res in results.items()
                  if res.status != "completed"]
        if losses:
            print(f"FAIL: lost across the process kill: {losses} "
                  f"({ {k: v.status for k, v in results.items()} })")
            return 1
        roll_res = results["smoke-roll"]
        if roll_res.value["digest"] != want.value["digest"]:
            print(f"FAIL: migrated digest "
                  f"{roll_res.value['digest']:#x} != uncontended "
                  f"{want.value['digest']:#x}")
            return 1
        if drill["migrated"] < 1 or not drill["readmitted"]:
            print(f"FAIL: kill drill did not migrate + readmit: "
                  f"{drill}")
            return 1

        restart = router.rolling_restart()
        jdirs = [str(p) for p in router.journal_dirs()]
        router.close()
        bad = [row for row in restart
               if not (row["readmitted"] and row["drained"])]
        if bad:
            print(f"FAIL: rolling restart rows not clean: {bad}")
            return 1
        pids1 = {row["slot"]: row["new_pid"] for row in restart}
        if any(pids1[s] == pids0.get(s) for s in pids1):
            print(f"FAIL: rolling restart reused a pid: {pids0} -> "
                  f"{pids1}")
            return 1

        # the journals are all that's left of the killed pid — the
        # whole story must reconstruct from disk alone
        fleet = postmortem.fleet_reconstruct(jdirs)
        if fleet["losses"]:
            print(f"FAIL: journaled losses after the drill: "
                  f"{fleet['losses']}")
            return 1
        rep = fleet["requests"].get("smoke-roll")
        if rep is None or not rep["complete"] or not rep["gap_free"]:
            print(f"FAIL: smoke-roll does not reconstruct "
                  f"complete+gap-free from the fleet journals: "
                  f"{rep and rep['problems']}")
            return 1
    print("PASS: SIGKILL'd procworker pid %s mid-rollout — 3/3 "
          "router promises completed, migrated digest bit-identical "
          "(%#010x), detection %.0f ms, %d route(s) migrated; rolling "
          "restart re-admitted %d fresh incarnation(s); fleet "
          "postmortem from %d journals: %d resolved, %d gap-free, 0 "
          "losses (%.1fs)"
          % (drill["old_pid"], roll_res.value["digest"],
             (drill["detect_s"] or 0) * 1e3, drill["migrated"],
             len(restart), len(jdirs), fleet["resolved"],
             fleet["gap_free"], time.time() - t0))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--child", action="store_true",
                    help="(internal) the killable service run")
    ap.add_argument("--dir", default=None,
                    help="(internal) journal directory")
    ap.add_argument("--multiworker", action="store_true",
                    help="worker-crash failover drill (2 workers, kill "
                         "one mid-batch, bit-identical migrated resume)")
    ap.add_argument("--postmortem", action="store_true",
                    help="swarmtrace drill: kill a worker, reconstruct "
                         "the migrated request's timeline from the "
                         "journal alone, assert gap-free")
    ap.add_argument("--procs", action="store_true",
                    help="process-mode drill: router + 2 procworker "
                         "processes, SIGKILL one mid-rollout, assert "
                         "zero-loss migration, bit-identical digest, "
                         "rolling restart, and a gap-free fleet "
                         "postmortem from the per-slot journals alone")
    args = ap.parse_args(argv)
    if args.child:
        return child(args.dir)
    if args.multiworker:
        return run_multiworker()
    if args.postmortem:
        return run_postmortem()
    if args.procs:
        return run_procs()
    return run_smoke()


if __name__ == "__main__":
    sys.exit(main())
