"""Device-bound serve rounds: donated staging buffers, submit-time row
prep, and a batched on-device unpack (docs/SERVICE.md §scheduling;
ROADMAP open item 2).

swarmtrace's latency breakdown (PR 9) showed the serve round was 93%
host work: per-leaf `jnp.stack` across the batch every round ("stack"),
per-job per-leaf slicing of the output batch ("unpack"), and problem
construction at round time ("pack"). This module collapses all three:

- **staging buffers**: each (worker, shape-bucket) keeps ONE resident
  stacked pytree (`BucketStaging.store`: SimState rows + Formation
  rows). A request joins the batch layout with a single compiled
  `write_row` call — donated, so the buffer is updated in place — and
  the round's output rows return to the store through one donated
  `scatter_rows`. Round-time "pack" is an index shuffle (`gather_rows`
  with the live slots), not a per-leaf restack.
- **submit-time prep**: admission builds the request's initial row
  (SimState + Formation) when the request is accepted, with the
  formation/safety/no-fault pieces cached per shape — the expensive
  problem construction leaves the round path entirely.
- **batched unpack**: `unpack_round` transposes the chunk positions to
  request-major and pairs them with the final batch positions in ONE
  compiled call, so the round's host sync is a single `device_get` of
  a result pytree instead of per-request slices.

All four jitted helpers are audited entry points
(`analysis.trace_audit`: transfer-free, cache-stable, f64-clean), and
the donated ones are registered in the jaxcheck JC005 donation
registry — a staging buffer read after donation is a lint error, not a
runtime surprise.

Concurrency contract (serve.service owns the locking): staging buffers
are mutated ONLY by the owning worker thread, with every donating call
made under the service lock after re-checking the worker's fence flag.
The failover supervisor reads rows (`take_row`) under the same lock
after fencing the worker — so a donated-away buffer can never be read,
and a fenced zombie can never donate a buffer the supervisor is
reading.
"""
from __future__ import annotations

import threading
from functools import partial
from typing import Any, List, Optional, Tuple

__all__ = ["BucketStaging", "write_row", "gather_rows", "scatter_rows",
           "take_row", "unpack_round", "cached_default_formation",
           "cached_sparams", "cached_no_faults", "cached_no_scenario",
           "pow2"]


def pow2(k: int) -> int:
    """Smallest power of two >= max(1, k) (the batch-shape rule every
    serve round has used since PR 6 — staging keeps the compiled batch
    shapes identical to the pack-at-round-time path's)."""
    p = 1
    while p < max(1, k):
        p *= 2
    return p


# small-index device constants, cached: every `jnp.asarray(i)` on the
# round path is a host->device transfer (~0.1 ms on this host) and the
# slot/index vocabulary is tiny (bounded by store capacity and padded
# batch size), so the same handful of constants recurs every round
_IDX_LOCK = threading.Lock()
_IDX_CACHE: dict = {}
_IDX_CACHE_MAX = 4096


def i32(value) -> Any:
    """A cached device-committed int32 scalar (int) or vector (tuple/
    list of ints) — the staging ops' index operands."""
    import jax.numpy as jnp

    key = tuple(value) if isinstance(value, (list, tuple)) else int(value)
    with _IDX_LOCK:
        arr = _IDX_CACHE.get(key)
    if arr is not None:
        return arr
    arr = jnp.asarray(list(key) if isinstance(key, tuple) else key,
                      jnp.int32)
    with _IDX_LOCK:
        if len(_IDX_CACHE) >= _IDX_CACHE_MAX:
            _IDX_CACHE.clear()      # tiny constants: rebuild is cheap
        return _IDX_CACHE.setdefault(key, arr)


# ---------------------------------------------------------------------------
# compiled staging ops (audited entry points; see analysis.trace_audit)
#
# Lazy jit: the module must import without jax (telemetry/bench paths
# import serve transitively), so the jitted callables are built on
# first use and cached at module scope.

_JIT_LOCK = threading.Lock()
_JITTED: dict = {}


def _jitted(name: str, build):
    fn = _JITTED.get(name)
    if fn is None:
        with _JIT_LOCK:
            fn = _JITTED.get(name)
            if fn is None:
                fn = _JITTED[name] = build()
    return fn


def _build_write_row():
    import jax

    @partial(jax.jit, donate_argnums=(0,))
    def write_row(store, row, slot):
        """Scatter one request's prepared row into the (donated)
        staging batch at ``slot`` — the admission-side half of the
        index shuffle."""
        return jax.tree.map(lambda b, r: b.at[slot].set(r), store, row)

    return write_row


def _build_gather_rows():
    import jax

    @jax.jit
    def gather_rows(store, idx):
        """Index-shuffle the round's batch out of the staging store
        (also the capacity-growth path). Read-only: the store survives
        for the rows that are not in this round."""
        return jax.tree.map(lambda b: b[idx], store)

    return gather_rows


def _build_scatter_rows():
    import jax

    @partial(jax.jit, donate_argnums=(0,))
    def scatter_rows(store, rows, slot_idx, row_idx):
        """Write the round's output rows back into the (donated)
        staging store: ``store[slot_idx[i]] = rows[row_idx[i]]``. The
        donation is what makes the staging buffer persistent — one
        allocation reused round after round."""
        return jax.tree.map(
            lambda b, r: b.at[slot_idx].set(r[row_idx]), store, rows)

    return scatter_rows


def _build_take_row():
    import jax

    @jax.jit
    def take_row(store, slot):
        """Materialize one resident row (failover migration and
        cross-incarnation re-staging read their state out with this)."""
        return jax.tree.map(lambda b: b[slot], store)

    return take_row


def _build_init_row():
    import jax

    from aclswarm_tpu import sim

    @jax.jit
    def init_row(q0, faults, scenario=None):
        """The serve request's initial SimState row as ONE compiled
        call: submit-time prep runs on client threads, and ~20 eager
        op dispatches per accepted request was measurable GIL pressure
        against the worker loop at saturation (~2 ms -> ~0.4 ms).
        ``scenario`` (None = the historical trace, bit for bit) attaches
        the request's scenario timeline — the serving layer always
        passes one (`cached_no_scenario` when the request scripts none)
        so scenario-free and scenario-ful requests share one compiled
        program, exactly the `no_faults` normalization."""
        return sim.init_state(q0, faults=faults, scenario=scenario)

    return init_row


def _build_unpack_round():
    import jax
    import jax.numpy as jnp

    @jax.jit
    def unpack_round(q_ticks, q_final):
        """Batched on-device unpack: chunk positions transposed to
        request-major (each row lands host-contiguous, so the per-job
        digest bytes match the legacy per-slice copies bit for bit)
        plus the final batch positions — one result pytree, ONE
        `device_get` per round."""
        return {"q_chunks": jnp.swapaxes(q_ticks, 0, 1),
                "q_final": q_final}

    return unpack_round


def write_row(store, row, slot):
    return _jitted("write_row", _build_write_row)(store, row, slot)


def gather_rows(store, idx):
    return _jitted("gather_rows", _build_gather_rows)(store, idx)


def scatter_rows(store, rows, slot_idx, row_idx):
    return _jitted("scatter_rows", _build_scatter_rows)(
        store, rows, slot_idx, row_idx)


def take_row(store, slot):
    return _jitted("take_row", _build_take_row)(store, slot)


def unpack_round(q_ticks, q_final):
    return _jitted("unpack_round", _build_unpack_round)(q_ticks, q_final)


def init_row(q0, faults, scenario=None):
    return _jitted("init_row", _build_init_row)(q0, faults, scenario)


# the raw (un-jitted via __wrapped__) functions for the trace audit:
# accessor names the audit registry binds to
def jitted_entry(name: str):
    """The jitted staging callable by name (trace_audit registration)."""
    builders = {"write_row": _build_write_row,
                "gather_rows": _build_gather_rows,
                "scatter_rows": _build_scatter_rows,
                "take_row": _build_take_row,
                "unpack_round": _build_unpack_round,
                "init_row": _build_init_row}
    return _jitted(name, builders[name])


# ---------------------------------------------------------------------------
# submit-time problem caches (the "pack leaves the round path" half)
#
# The default serve problem pieces are pure functions of (n, dtype):
# caching them moves the expensive construction off BOTH the round path
# and the per-request submit path. Values are bit-identical to fresh
# construction (same inputs, same ops), so staged results match the
# legacy path exactly.

_CACHE_LOCK = threading.Lock()
_FORM_CACHE: dict = {}
_SPARAMS_CACHE: dict = {}
_FAULTS_CACHE: dict = {}
_SCEN_CACHE: dict = {}


def _dt_key(dt) -> str:
    import numpy as np
    return np.dtype(dt).name


def cached_default_formation(n: int, dt):
    """The serve default formation (circle + complete graph + identity
    gains) for fleet size ``n`` — shared read-only across requests."""
    import jax.numpy as jnp
    import numpy as np

    from aclswarm_tpu.core.types import make_formation

    key = (int(n), _dt_key(dt))
    with _CACHE_LOCK:
        form = _FORM_CACHE.get(key)
    if form is not None:
        return form
    ang = np.linspace(0, 2 * np.pi, n, endpoint=False)
    pts = np.stack([3 * np.cos(ang), 3 * np.sin(ang),
                    np.full(n, 2.0)], 1)
    adj = np.ones((n, n)) - np.eye(n)
    gains = (np.eye(n)[:, :, None, None] * np.eye(3)[None, None]
             * 0.01)
    form = make_formation(jnp.asarray(pts, dt), jnp.asarray(adj, dt),
                          jnp.asarray(gains, dt))
    with _CACHE_LOCK:
        return _FORM_CACHE.setdefault(key, form)


def cached_sparams(dt):
    import jax.numpy as jnp

    from aclswarm_tpu.core.types import SafetyParams

    key = _dt_key(dt)
    with _CACHE_LOCK:
        sp = _SPARAMS_CACHE.get(key)
    if sp is not None:
        return sp
    sp = SafetyParams(
        bounds_min=jnp.asarray([-50.0, -50.0, 0.0], dt),
        bounds_max=jnp.asarray([50.0, 50.0, 10.0], dt))
    with _CACHE_LOCK:
        return _SPARAMS_CACHE.setdefault(key, sp)


def cached_no_faults(n: int, dt):
    from aclswarm_tpu.faults import schedule as faultlib

    key = (int(n), _dt_key(dt))
    with _CACHE_LOCK:
        fs = _FAULTS_CACHE.get(key)
    if fs is not None:
        return fs
    fs = faultlib.no_faults(n, dtype=dt)
    with _CACHE_LOCK:
        return _FAULTS_CACHE.setdefault(key, fs)


def cached_no_scenario(n: int, dt):
    """The inert scenario every scenario-free serve rollout carries
    (`scenarios.no_scenario` at the serve-wide axis caps): ONE pytree
    structure per bucket, so scenario-ful and scenario-free requests
    stack into the same batch — `no_scenario` is bit-identical to
    ``scenario=None`` (tests/test_scenarios.py)."""
    from aclswarm_tpu.scenarios import no_scenario

    key = (int(n), _dt_key(dt))
    with _CACHE_LOCK:
        sc = _SCEN_CACHE.get(key)
    if sc is not None:
        return sc
    sc = no_scenario(n, dtype=dt)
    with _CACHE_LOCK:
        return _SCEN_CACHE.setdefault(key, sc)


def clear_caches() -> None:
    """Drop the problem + index caches (tests that flip the x64 flag
    or tear down jax backends)."""
    with _CACHE_LOCK:
        _FORM_CACHE.clear()
        _SPARAMS_CACHE.clear()
        _FAULTS_CACHE.clear()
        _SCEN_CACHE.clear()
    with _IDX_LOCK:
        _IDX_CACHE.clear()


# ---------------------------------------------------------------------------
# per-(worker, bucket) staging state

class BucketStaging:
    """One worker incarnation's resident batch for one shape bucket.

    ``store`` is a ``(state_rows, form_rows)`` tuple pytree with a
    leading capacity axis; ``slots[i]`` names the `_Job` resident in
    row ``i`` (None = free). ``shared`` is the bucket's
    ``(ControlGains, SafetyParams, SimConfig)`` — identical for every
    request in the bucket by construction of the bucket key.

    The service mutates instances only from the owning worker thread
    under its lock (see the module docstring's concurrency contract);
    this class is deliberately just data + slot arithmetic.

    Capacity is FIXED at creation (the service uses
    ``2 * pow2(max_batch)`` — the double-buffered working set: one
    round in flight plus one being packed). A bounded capacity keeps
    the compiled shape set of the staging ops closed — every
    (capacity, batch) combination is warmable once — where an
    unbounded store re-compiled gather/scatter at every growth step
    (measured as a compile storm inside the throughput window).
    Residency is an LRU cache: when the store is full, the service
    evicts a non-busy resident back to a per-job row (`take_row`) and
    reuses its slot.
    """

    __slots__ = ("store", "slots", "shared", "device")

    def __init__(self, device=None, shared=None):
        self.store: Optional[Tuple[Any, Any]] = None
        self.slots: List[Any] = []
        self.shared = shared
        self.device = device

    @property
    def capacity(self) -> int:
        return len(self.slots)

    def occupied(self) -> int:
        return sum(1 for j in self.slots if j is not None)

    def free_slots(self) -> List[int]:
        return [i for i, j in enumerate(self.slots) if j is None]

    # ---------------------------------------------------- store plumbing

    def create(self, row: Tuple[Any, Any], cap: int) -> None:
        """Allocate the store: zeros shaped like ``row`` with a leading
        ``cap`` axis, committed to this staging's device."""
        import jax
        import jax.numpy as jnp

        store = jax.tree.map(
            lambda r: jnp.zeros((cap,) + r.shape, r.dtype), row)
        if self.device is not None:
            store = jax.device_put(store, self.device)
        self.store = store
        self.slots = [None] * cap
