"""Client-side conveniences for swarmserve (docs/SERVICE.md).

The service API is `SwarmService.submit` -> `Ticket`; this module adds
the handful of patterns every caller was about to re-implement:

- `probe_backend`: the sacrificial-subprocess device probe (a wedged
  tunnel hangs `jax.devices()` *uncancellably* in the calling process —
  bench.py learned this the hard way in round 5) wrapped in the unified
  `RetryPolicy`, returning the backend NAME so callers can mark
  not-the-bench-device runs as degraded instead of publishing them as
  device measurements;
- `submit_and_wait`: submit-then-block with every non-answer translated
  into a structured failed `Result` — admission rejection, bounded
  client patience (the service still owes the result; the client just
  stopped waiting), and a DEAD worker (a ticket a dead worker holds
  will never resolve; journal recovery is how it gets honored) — so
  callers like `trials_suite.py --serve` treat every path uniformly.
"""
from __future__ import annotations

import time
import uuid
import zlib
from typing import Optional

from aclswarm_tpu.serve.api import (E_CLIENT_TIMEOUT, E_QUEUE_FULL,
                                    E_WORKER_DIED, FAILED, RejectedError,
                                    Result, ServeError)
from aclswarm_tpu.utils.retry import (RetryPolicy, retry_after_delay,
                                      retry_call, subprocess_output)

PROBE_CODE = "import jax; print('backend=' + jax.default_backend())"


def probe_backend(timeout_s: float = 120.0,
                  code: str = PROBE_CODE,
                  policy: Optional[RetryPolicy] = None,
                  cwd: Optional[str] = None) -> Optional[str]:
    """Backend name (``'tpu'``/``'cpu'``/...) via a throwaway subprocess
    (`utils.retry.subprocess_output` — the single home for the
    sacrificial-probe mechanics), retried under the unified policy;
    None = the backend never answered within the budget (the
    tunnel-wedge signature)."""
    policy = policy or RetryPolicy(attempts=2, base_s=1.0, max_s=5.0)

    def _once() -> str:
        out = subprocess_output(code, timeout_s, cwd=cwd)
        if out is None:
            raise RuntimeError("device probe gave no output within "
                               f"{timeout_s:.0f} s")
        for line in out.splitlines():
            if line.startswith("backend="):
                return line.split("=", 1)[1].strip()
        raise RuntimeError("device probe exited without a backend line")

    try:
        return retry_call(_once, policy=policy)
    except RuntimeError:
        return None


def submit_and_wait(service, kind: str, params: dict, *,
                    tenant: str = "default",
                    request_id: Optional[str] = None,
                    deadline_s: Optional[float] = None,
                    client_timeout_s: Optional[float] = None,
                    poll_s: float = 5.0,
                    trace_id: Optional[str] = None,
                    reject_retries: int = 4,
                    max_retry_wait_s: float = 30.0) -> Result:
    """Submit one request and block for its terminal `Result`. Every
    non-answer comes back as a structured result (status ``failed``) so
    callers can treat every path uniformly — only programming errors
    raise:

    - admission rejection -> retried: the service's ``retry_after_s``
      hint is HONORED (slept out with deterministic crc32 jitter,
      `utils.retry.jittered`, so replays are identical and a rejected
      fleet de-aligns) up to ``reject_retries`` times before the caller
      sees a structured ``queue_full`` — backpressure becomes a short
      wait, not a failure every caller re-implements around
      (``reject_retries=0`` restores the old surface-it-raw behavior);
    - ``client_timeout_s`` lapsing -> ``client_timeout`` (the service
      STILL owes the result; the client just stopped waiting);
    - the worker dying with the ticket open -> ``worker_died`` (a dead
      worker never resolves its tickets — waiting longer is a hang, and
      journal recovery is how the promise gets honored).

    The wait polls ``service.alive`` every ``poll_s`` — legitimate
    long-running work is indistinguishable from a hang without it.
    ``trace_id`` threads a caller-held swarmtrace id through to the
    service (suites tracing their own cells); omitted, the service
    mints one and the terminal `Result.trace_id` carries it back."""
    # the id is minted HERE when the caller brought none: it is both
    # the idempotency key across the retries and the jitter seed — a
    # fleet of auto-id callers must NOT share one crc32(tenant:kind)
    # seed, or their retries march in lockstep (the herd the jitter
    # exists to break)
    request_id = request_id or uuid.uuid4().hex[:12]
    seed = zlib.crc32(request_id.encode())
    ticket = None
    for attempt in range(max(0, reject_retries) + 1):
        try:
            ticket = service.submit(kind, params, tenant=tenant,
                                    request_id=request_id,
                                    deadline_s=deadline_s,
                                    trace_id=trace_id)
            break
        except RejectedError as e:
            if attempt >= reject_retries:
                return Result(request_id=request_id or "", status=FAILED,
                              error=ServeError(
                                  E_QUEUE_FULL, str(e),
                                  detail={"retry_after_s":
                                          e.retry_after_s}))
            time.sleep(retry_after_delay(e.retry_after_s, seed,
                                         attempt, max_retry_wait_s))
    assert ticket is not None
    deadline = (time.monotonic() + client_timeout_s
                if client_timeout_s is not None else None)
    while True:
        step = poll_s
        if deadline is not None:
            step = min(step, max(0.0, deadline - time.monotonic()))
        try:
            return ticket.result(timeout=step)
        except TimeoutError as e:
            if not service.alive and not ticket.done:
                return Result(
                    request_id=ticket.request_id, status=FAILED,
                    error=ServeError(
                        E_WORKER_DIED,
                        "serve worker died with this request in flight "
                        "(scripted crash?) — journal recovery is how "
                        "it gets honored"))
            if deadline is not None and time.monotonic() >= deadline:
                return Result(request_id=ticket.request_id, status=FAILED,
                              error=ServeError(E_CLIENT_TIMEOUT, str(e)))
