"""swarmserve: the always-on serving layer over the batched engine
(docs/SERVICE.md; ROADMAP open item 2).

A `SwarmService` is a threaded queue front end plus N SUPERVISED device
workers (`serve.workers.WorkerPool` — one per mesh slice, or N host
threads on the CPU fallback host; ``ServiceConfig.workers``). Clients
`submit` heterogeneous requests — chunked rollouts, assignment solves,
gain designs, registered extension kinds — and hold a `Ticket` that
streams per-chunk progress and resolves to a terminal `Result`. Each
worker packs compatible rollout requests into shape-bucketed,
power-of-two-padded device batches (the `harness/trials.py` compaction
idiom run in reverse: the batch is *refilled* from the queue every
chunk instead of compacted as trials die) — admission SHARDS buckets
across workers by rendezvous hash, so one compiled shape lives on
exactly one worker — and runs them through `sim.batched_rollout` one
chunk at a time, so every chunk boundary is simultaneously:

- a **scheduling point** (new arrivals join the next round — continuous
  batching, the Orca-style iteration-level scheduler of PAPERS.md),
- a **deadline gate** (expired requests terminate with a structured
  `deadline_exceeded` error instead of hanging),
- a **preemption point** (a job past its quantum with other work
  waiting is evicted THROUGH the resilience checkpoint codec and
  resumes bit-identically — PR 5 made eviction free), and
- a **durability point** (with a journal, in-flight rollout state is
  checkpointed so a SIGKILLed worker loses at most one chunk of work,
  never a request).

Robustness invariants (proven by `serve.smoke`, `tests/test_serve.py`,
and `benchmarks/serve_soak.py`):

1. bounded queues — admission rejects loudly with a retry-after hint,
   the service never buffers unboundedly (`serve.admission`);
2. zero silent losses — an accepted request is journaled before
   `submit` returns and terminates with a value or structured error,
   across worker SIGKILL + restart;
3. bit-identical resume — preempted or crash-recovered rollouts match
   an uninterrupted run exactly;
4. degraded, not dead — transient device failures retry and fall back
   to CPU with loud markers via the shared `ChunkExecutor`;
5. worker death is routine — a killed worker's in-flight jobs fail
   over through the checkpoint codec to surviving workers (heartbeat +
   lease detection, poison ping-pong bound, backoff-gated rejoin:
   `serve.workers`), proven by `serve.smoke --multiworker` and
   `benchmarks/serve_multiworker_soak.py`.

Host-side only: this module adds no compiled code (the HLO baseline is
unchanged); it drives the same jitted entry points the trial drivers
use.
"""
from __future__ import annotations

import dataclasses
import os
import sys
import threading
import time
import uuid
import zlib
from pathlib import Path
from typing import Any, Callable, Optional

import numpy as np

from aclswarm_tpu.resilience import ChunkExecutor, maybe_crash
from aclswarm_tpu.resilience import checkpoint as ckptlib
from aclswarm_tpu.serve import staging as stagelib  # noqa: F401 (submodule
#                                import — staging has no back-import, so
#                                this is cycle-safe during package init)
from aclswarm_tpu.serve.admission import AdmissionControl
from aclswarm_tpu.serve.api import (COMPLETED, E_CANCELLED, E_DEADLINE,
                                    E_EXECUTION, E_POISONED, E_QUEUE_FULL,
                                    E_SHUTDOWN, FAILED, PREEMPTED, QUEUED,
                                    RUNNING, TIMED_OUT, ChunkEvent,
                                    RejectedError, Request, Result,
                                    ServeError, Ticket)
from aclswarm_tpu.serve.stats import ServeStats
from aclswarm_tpu.telemetry import (LifecycleLog, MetricsRegistry,
                                    install_crash_dump, mint_trace_id)
from aclswarm_tpu.utils import get_logger
from aclswarm_tpu.utils.locks import OrderedLock
from aclswarm_tpu.utils.retry import RetryPolicy

BUILTIN_KINDS = ("rollout", "assign", "gains", "stats", "scenario",
                 "health")
CRASH_SITE = "serve"        # maybe_crash site: one boundary per round

# lifecycle events journaled even with cfg.trace=False: the PR-8
# worker-failure ledger recovery restores its counters from (turning
# tracing off must not also turn off the failover evidence), and the
# swarmwatch alert stream (turning tracing off must not blind the
# detection evidence the slo_detection artifact is built from)
_LEDGER_EVENTS = frozenset({"failover", "migrated", "poisoned", "alert"})


@dataclasses.dataclass(frozen=True)
class ServiceConfig:
    """Service-level knobs (per-request knobs live in the params)."""

    max_queue_per_tenant: int = 8     # admission cap per tenant
    max_queue_total: int = 32         # admission cap across tenants
    max_batch: int = 4                # device batch slots per round
    quantum_chunks: int = 2           # chunks before a job is preemptible
    # ---- multi-worker serving (serve.workers; docs/SERVICE.md) ----
    workers: int = 1                  # supervised device workers (one per
    #                                   mesh slice; N threads on CPU)
    lease_s: float = 60.0             # heartbeat lease: a worker silent
    #                                   this long is declared dead even
    #                                   with its thread alive (wedge);
    #                                   generous by default — a first
    #                                   compile legitimately blocks the
    #                                   loop for tens of seconds
    supervise_poll_s: float = 0.1     # supervisor cadence
    max_worker_exclusions: int = 2    # K SOLO-implicated kills (the job
    #                                   was alone in the batch — nobody
    #                                   else to blame) before a request
    #                                   is declared poisoned; batched
    #                                   kills quarantine but don't count
    max_worker_restarts: int = 3      # circuit breaker: consecutive
    #                                   deaths before a slot retires
    rejoin_base_s: float = 0.05       # backoff-gated rejoin (RetryPolicy)
    rejoin_max_s: float = 2.0
    # journal directory (None = in-memory only: preemption still goes
    # through the codec, but a killed worker process loses the promise
    # ledger — production serving always sets this)
    journal_dir: Optional[str] = None
    default_deadline_s: Optional[float] = None
    idle_poll_s: float = 0.05         # worker park interval when idle
    retry_attempts: int = 3           # ChunkExecutor transient retries
    cpu_fallback: bool = True         # degrade-don't-die (loud markers)
    # terminal results kept in memory for duplicate-submit idempotency
    # (oldest evicted beyond this — an always-on service must not grow
    # per-request state without bound; journal done-frames persist
    # regardless, so recovery-time replay is unaffected)
    done_retention: int = 1024
    # swarmtrace (docs/OBSERVABILITY.md §swarmtrace): journal the full
    # lifecycle-event stream (submitted/batched/chunk/.../resolved) to
    # the journal's events.log. Off disables only the trace events —
    # the failover/migrated/poisoned ledger PR 8 recovery counts from
    # is always journaled. The off switch exists for the overhead A/B
    # (`benchmarks/trace_soak.py`); production keeps it on (<2% of the
    # serve path, enforced by the committed artifact's schema).
    trace: bool = True
    # ---- device-bound rounds (serve.staging; docs/SERVICE.md) ----
    # staging=True: requests are prepped into batch-layout rows at
    # submit, rounds run off persistent donated staging buffers, and
    # the round's host sync is ONE device_get of a compacted result
    # pytree. False = the PR-9 pack-at-round-time path (kept as the
    # bit-parity reference; tests/test_serve.py::TestStagedParity).
    staging: bool = True
    # pipeline=True: double-buffered rounds — the worker packs and
    # dispatches round k+1 while the device still runs round k, and
    # blocks only at resolve (the single device_get). False = resolve
    # each round before picking the next (staged but sequential).
    # Requires staging; ignored when staging=False.
    pipeline: bool = True
    # ---- swarmwatch (telemetry.timeseries/slo; docs/OBSERVABILITY.md
    # §swarmwatch): continuous time-series over this service's registry
    # + live SLO evaluation with a pending→firing→resolved alert state
    # machine. Off by default (a sampler thread per service would tax
    # every short-lived test service); production/soak services turn it
    # on. With a journal, history persists to <journal>/timeseries.log
    # (the resilience frame log — survives SIGKILL, readable from disk
    # alone) and alert transitions append to events.log as schema'd
    # ``alert`` fleet events.
    watch: bool = False
    watch_interval_s: float = 0.25    # sampler + SLO evaluation cadence
    watch_capacity: int = 1024        # points retained per series
    # SLO catalog override (tuple of telemetry.slo.SloSpec); None =
    # telemetry.slo.default_slos(max_queue_total=cfg.max_queue_total)
    slos: Optional[tuple] = None
    # ---- process mode (serve.procworker / serve.router;
    # docs/SERVICE.md §process mode): this service's journal
    # incarnation. Thread mode leaves it 0; a procworker hosting one
    # router slot carries the slot's spawn generation, so every req/
    # done frame and lifecycle event is stamped with the PROCESS
    # generation that wrote it, and a FENCE frame in the shared
    # per-slot journal dir (written by the successor incarnation
    # before it recovers) turns a zombie predecessor's journal writes
    # into loud no-ops instead of corruption.
    incarnation: int = 0


@dataclasses.dataclass
class _Job:
    """Service-internal request state (the ticket is the client view)."""

    req: Request
    ticket: Ticket
    bucket: tuple
    status: str = QUEUED
    spec: Any = None              # parsed rollout problem (lazy-built)
    state: Any = None             # resident SimState between chunks
    chunks_total: int = 0
    chunks_done: int = 0
    run_chunks: int = 0           # consecutive chunks this residency
    preemptions: int = 0
    resumed: bool = False         # continued from a journaled checkpoint
    crc: int = 0                  # running bit-exact position digest
    chunk_digests: list = dataclasses.field(default_factory=list)
    t_accept: float = 0.0         # monotonic (in-process latency split)
    t_first_run: Optional[float] = None
    finished: bool = False        # _finish() ran (atomic once-guard)
    held: bool = False            # caps slot reserved, picker-invisible
    worker: Optional[int] = None  # slot currently holding the job
    pick_batch: int = 1           # size of the batch this job was last
    #                               PICKED into — the poison bound's
    #                               solo-attribution unit (with the
    #                               pipeline a dead worker usually has
    #                               TWO rounds in flight, so "only
    #                               orphan" would never be true and a
    #                               poison request would ping-pong the
    #                               fleet unbounded; "alone in its own
    #                               batch" is the honest blame unit)
    epoch: int = 0                # bumped on failover: a fenced zombie
    #                               worker's stale writes are no-ops
    failovers: int = 0            # worker-death migrations survived
    excluded_workers: set = dataclasses.field(default_factory=set)
    #                               worker INCARNATIONS this job died on
    #                               (the poison ping-pong bound)
    suspect: bool = False         # was in-flight at a worker death:
    #                               QUARANTINED to solo batches until a
    #                               surviving chunk exonerates it — an
    #                               innocent batch-mate of a kill must
    #                               never ride to the poison bound
    solo_kills: int = 0           # kills witnessed while SOLO in the
    #                               batch (nobody else to blame) since
    #                               the last exoneration — the poison
    #                               bound counts only these
    cancelled: Optional[str] = None    # boundary-cancel reason (wire
    #                                    client death; never mid-batch)
    _ckpt_bytes: Optional[bytes] = None   # journal-less preemption frame
    _problem: Any = None          # (formation, cgains, sparams, cfg)
    staged: Any = None            # (BucketStaging, slot) while resident
    #                               in a worker's staging store — the
    #                               job's state IS that row (job.state
    #                               stays None); cleared on preemption,
    #                               failover, and every terminal path
    _shadow: Any = None           # unjournaled failover source: a LAZY
    #                               (output-batch, row) reference set at
    #                               every resolved chunk — always
    #                               state@chunks_done by construction;
    #                               materialized (one take_row) only if
    #                               a migration actually needs it


class _Fenced(Exception):
    """Raised inside a round when the executing worker discovers it has
    been fenced (lease-lapse zombie): the thread must abandon the round
    WITHOUT touching staging buffers or job state — its in-flight jobs
    were (or are being) failed over by the supervisor."""


@dataclasses.dataclass
class _PendingRound:
    """One dispatched-but-unresolved staged rollout round (the unit the
    worker loop double-buffers). Everything `_round_finish` needs:
    the async device handles, the job/row/slot maps, and the OPEN
    parent span (entered at pack, exited at resolve — so the committed
    breakdown's ``serve.round`` covers the whole pipelined window)."""

    pairs: list                # the original (job, epoch) pick
    jobs: list                 # gated-in jobs, batch-row order
    epochs: dict               # id(job) -> epoch at pick
    rows: dict                 # id(job) -> row index in the round batch
    out: Any                   # output batch SimState (async device)
    unpacked: Any              # {"q_chunks","q_final"} (async device)
    staging: Any               # the BucketStaging this round ran from
    chunk: int                 # ticks per chunk (bucket-pinned)
    B: int                     # live batch size (pre-pow2-pad)
    P: int                     # padded batch size actually dispatched
    t0: float                  # monotonic at dispatch
    grnd: int                  # global round number (span/journal attr)
    wround: int                # worker-round AT DISPATCH (the chunk
    #                            event must name the round that ran it,
    #                            not whatever round started since)
    span_attrs: dict           # serve.round span attributes
    start_dur: float           # wall of the START phase: the round
    #                            span is emitted at finish as
    #                            start_dur + finish_dur — its two
    #                            ACTIVE phases only, NOT the pipelined
    #                            idle window in between (which belongs
    #                            to the interleaved round). Keeps
    #                            sum(serve.round) <= wall, so the
    #                            stage fractions the committed
    #                            breakdown/throughput gates consume
    #                            are not diluted ~2x by overlap.


# ---------------------------------------------------------------------------
# request parsing / problem building (rollout)

@dataclasses.dataclass
class _RolloutSpec:
    n: int
    chunk_ticks: int
    n_chunks: int
    assignment: str
    assign_every: int
    seed: int
    faults_spec: Optional[dict]
    scenario_spec: Optional[dict]
    points: Optional[np.ndarray]
    adjmat: Optional[np.ndarray]
    gains: Optional[np.ndarray]


def _parse_rollout(params: dict) -> _RolloutSpec:
    """Validate + normalize rollout params at ADMISSION time: a request
    the engine cannot run is refused at the door (ValueError), not
    accepted and failed later."""
    if "n" not in params or "ticks" not in params:
        raise ValueError("rollout params require 'n' and 'ticks'")
    n = int(params["n"])
    ticks = int(params["ticks"])
    chunk = int(params.get("chunk_ticks", 20))
    if n < 2 or ticks < 1 or chunk < 1:
        raise ValueError(f"bad rollout sizes n={n} ticks={ticks} "
                         f"chunk_ticks={chunk}")
    assign_every = int(params.get("assign_every", chunk))
    if chunk % assign_every:
        # the batch shares the decimation phase (docs/BATCHED_TRIALS.md):
        # chunk-aligned auctions are what let heterogeneous requests at
        # different progress share one compiled program
        raise ValueError(f"chunk_ticks ({chunk}) must be a multiple of "
                         f"assign_every ({assign_every})")
    if ticks % chunk:
        # every chunk runs full-length (ONE compiled shape per bucket);
        # rounding up silently would execute MORE ticks than requested
        # and report a different problem than the one submitted
        raise ValueError(f"ticks ({ticks}) must be a multiple of "
                         f"chunk_ticks ({chunk}) — chunks run whole")
    fspec = params.get("faults")
    _FKEYS = {"dropout_frac", "drop_tick", "rejoin_tick", "link_loss"}
    if fspec is not None and (not isinstance(fspec, dict)
                              or not set(fspec) <= _FKEYS):
        raise ValueError("rollout 'faults' must be a spec dict with keys "
                         f"from {sorted(_FKEYS)}, got {fspec!r}")
    sspec = params.get("scenario")
    if sspec is not None:
        # scenario requests validate against the registry AT ADMISSION
        # — an unknown family or out-of-space override is refused at
        # the door like any other malformed rollout (docs/SCENARIOS.md)
        _SKEYS = {"family", "seed", "params", "horizon"}
        if not isinstance(sspec, dict) or "family" not in sspec \
                or not set(sspec) <= _SKEYS:
            raise ValueError(
                "rollout 'scenario' must be a spec dict {'family': "
                f"<registry name>, 'seed'?, 'params'?, 'horizon'?}}, "
                f"got {sspec!r}")
        from aclswarm_tpu.scenarios import registry as scenreg
        fam = scenreg.validate(str(sspec["family"]), sspec.get("params"))
        if fam.localization != "truth":
            # the serving engine runs the 'truth' information model
            # (no estimate tables in the serve rows): a family whose
            # axes only bite under flooded localization would run as a
            # silent no-op — scenario-free results sold as a scenario
            # run. Refuse at the door; the trials/suite drivers serve
            # those families (docs/SCENARIOS.md).
            raise ValueError(
                f"scenario family {sspec['family']!r} requires the "
                f"{fam.localization!r} information model; serve "
                "rollouts run 'truth' localization — drive it through "
                "harness.trials or benchmarks/scenario_suite.py")
    arr = {k: (np.asarray(params[k]) if k in params else None)
           for k in ("points", "adjmat", "gains")}
    return _RolloutSpec(
        n=n, chunk_ticks=chunk,
        n_chunks=ticks // chunk,
        assignment=str(params.get("assignment", "auction")),
        assign_every=assign_every, seed=int(params.get("seed", 0)),
        faults_spec=fspec, scenario_spec=sspec, points=arr["points"],
        adjmat=arr["adjmat"], gains=arr["gains"])


def _bucket_from_spec(spec: _RolloutSpec) -> tuple:
    return ("rollout", spec.n, spec.chunk_ticks, spec.assignment,
            spec.assign_every)


def _scenario_to_rollout(params: dict) -> dict:
    """The `scenario` request kind is a rollout drawn from the family
    registry: flat params carry the rollout sizing keys (n, ticks, ...)
    plus the scenario draw (family, seed, params, horizon). Normalized
    here into rollout params with a nested scenario spec, so scenario
    requests share the rollout state machine — and the rollout BUCKETS:
    a scenario request batches with plain rollouts of the same shape
    (the `no_scenario` normalization; docs/SCENARIOS.md)."""
    if not isinstance(params, dict) or "family" not in params:
        raise ValueError("scenario params require 'family' (a registry "
                         "family name) plus the rollout sizing keys "
                         "('n', 'ticks', ...)")
    p = dict(params)
    sspec = {k: p.pop(k) for k in ("family", "params", "horizon")
             if k in p}
    if "seed" in p:
        # ONE seed drives both draws: the scenario script and the
        # rollout's initial cloud (reproducible from the flat params)
        sspec["seed"] = p["seed"]
    p["scenario"] = sspec
    return p


def bucket_of(kind: str, params: dict) -> tuple:
    """The shape-compatibility key a request will be scheduled under —
    the SAME encoding `_make_job` assigns (built on `_parse_rollout`,
    defaults included). The failover drills aim worker-targeted kills
    at a bucket's placed owner (`serve.smoke --multiworker`,
    `benchmarks/serve_multiworker_soak.py`); this is the one helper
    they and the service share, so the drills can never drift from the
    scheduler's own bucketing. Raises ValueError for params the
    service would refuse."""
    if kind == "rollout":
        return _bucket_from_spec(_parse_rollout(params))
    if kind == "scenario":
        return _bucket_from_spec(
            _parse_rollout(_scenario_to_rollout(params)))
    return ("single", kind)


def _rollout_problem(spec: _RolloutSpec):
    """Seeded problem construction (shared with `resilience.smoke`'s
    idiom): circle formation + complete graph unless the request shipped
    explicit arrays; initial cloud from the request seed. Deterministic
    from the spec alone — that is what makes crash re-execution and
    resume proofs possible.

    The default formation / safety params / no-fault schedule are
    served from `serve.staging`'s per-shape caches (same inputs, same
    ops — bit-identical values): submit-time prep runs this on the
    client thread, so the shared pieces must not be rebuilt per
    request."""
    import jax.numpy as jnp

    from aclswarm_tpu import sim
    from aclswarm_tpu.core.types import ControlGains, make_formation
    from aclswarm_tpu.faults import schedule as faultlib

    n = spec.n
    dt = jnp.result_type(float)
    if (spec.points is None and spec.adjmat is None
            and spec.gains is None):
        form = stagelib.cached_default_formation(n, dt)
    else:
        if spec.points is not None:
            pts = np.asarray(spec.points, float)
        else:
            ang = np.linspace(0, 2 * np.pi, n, endpoint=False)
            pts = np.stack([3 * np.cos(ang), 3 * np.sin(ang),
                            np.full(n, 2.0)], 1)
        adj = (np.asarray(spec.adjmat, float) if spec.adjmat is not None
               else np.ones((n, n)) - np.eye(n))
        gains = (np.asarray(spec.gains, float) if spec.gains is not None
                 else np.eye(n)[:, :, None, None] * np.eye(3)[None, None]
                 * 0.01)
        form = make_formation(jnp.asarray(pts, dt), jnp.asarray(adj, dt),
                              jnp.asarray(gains, dt))
    sparams = stagelib.cached_sparams(dt)
    rng = np.random.default_rng(spec.seed)
    q0 = rng.normal(size=(n, 3)) * 2.0 + [0, 0, 2.0]
    # every serve rollout carries a FaultSchedule (no_faults when the
    # request scripts none): ONE pytree structure per bucket, so faulted
    # and fault-free requests stack into the same batch — no_faults is
    # bit-identical to faults=None (tests/test_faults.py)
    if spec.faults_spec is not None:
        fs = faultlib.sample_schedule(spec.seed, n, dtype=dt,
                                      **spec.faults_spec)
    else:
        fs = stagelib.cached_no_faults(n, dt)
    # ... and a Scenario (no_scenario when the request scripts none) —
    # the same normalization, one axis up: scenario requests draw from
    # the family registry at the SERVE-WIDE caps, so scenario-ful and
    # scenario-free requests share one compiled program per bucket
    # (no_scenario is bit-identical to scenario=None;
    # tests/test_scenarios.py, docs/SCENARIOS.md)
    if spec.scenario_spec is not None:
        from aclswarm_tpu.scenarios import registry as scenreg
        ss = spec.scenario_spec
        # the horizon defaults to the REQUEST's own tick count: family
        # event fractions then land inside the rollout being served (a
        # fixed default would quietly schedule every event past a
        # short request's end — a scenario-free run sold as a scenario)
        scen = scenreg.sample(
            str(ss["family"]), int(ss.get("seed", spec.seed)), n,
            dtype=dt,
            horizon=int(ss.get("horizon",
                               spec.n_chunks * spec.chunk_ticks)),
            params=ss.get("params"))
    else:
        scen = stagelib.cached_no_scenario(n, dt)
    # ONE compiled call instead of ~20 eager dispatches: prep runs on
    # client threads at submit, where eager-op GIL pressure was
    # measurable against the worker loop at saturation
    state = stagelib.init_row(jnp.asarray(q0, dt), fs, scen)
    cfg = sim.SimConfig(assignment=spec.assignment,
                        assign_every=spec.assign_every)
    return state, form, ControlGains(), sparams, cfg


# ---------------------------------------------------------------------------
# journal frames (atomic, codec-framed — no pickle)

def _write_frame(path: Path, payload, manifest: dict) -> None:
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_name(path.name + ".tmp")
    tmp.write_bytes(ckptlib.dumps(payload, manifest))
    os.replace(tmp, path)


def _read_frame(path: Path):
    return ckptlib.loads(path.read_bytes(), path)


# incarnation fence (process mode, docs/SERVICE.md §process mode): one
# codec frame in the journal dir naming the minimum incarnation allowed
# to write there
FENCE_NAME = "FENCE"


def write_fence(journal_dir, incarnation: int) -> None:
    """Stamp ``journal_dir`` as owned by ``incarnation`` (atomic codec
    frame). The SUCCESSOR writes this before it recovers the journal:
    a predecessor process that missed its lease but is still running
    observes the fence within `SwarmService.FENCE_CHECK_S` and every
    later journal write from it becomes a loud no-op — the
    declare-dead→respawn sequence never waits on the zombie actually
    exiting."""
    _write_frame(Path(journal_dir) / FENCE_NAME, {},
                 ckptlib.make_manifest("serve_fence", "-", chunk=0,
                                       incarnation=int(incarnation)))


def read_fence(journal_dir) -> Optional[int]:
    """The incarnation currently fencing ``journal_dir`` (None when
    unfenced or unreadable — an unreadable fence fails OPEN: refusing
    writes on a torn fence would turn a crash mid-`write_fence` into a
    permanently wedged slot)."""
    path = Path(journal_dir) / FENCE_NAME
    try:
        if not path.is_file():
            return None
        _, man = _read_frame(path)
    except (OSError, ckptlib.CheckpointError):
        return None
    inc = man.get("incarnation")
    return int(inc) if inc is not None else None


class SwarmService:
    """The in-process serving front end + device worker (docs/SERVICE.md).

    Lifecycle::

        svc = SwarmService(ServiceConfig(journal_dir=...))
        t = svc.submit("rollout", {"n": 5, "ticks": 100}, tenant="a",
                       deadline_s=30.0)
        for ev in t.stream(): ...          # per-chunk progress
        res = t.result(timeout=60)         # value OR structured error
        svc.close()                        # drain, then stop — clean
                                           # shutdown once all tenants idle

    ``start=False`` builds the service without launching the worker
    (admission-control tests and staged recovery drills)."""

    def __init__(self, cfg: ServiceConfig = ServiceConfig(), *,
                 start: bool = True, log=None):
        self.cfg = cfg
        self.log = log or get_logger("serve")
        self._adm = AdmissionControl(cfg.max_queue_per_tenant,
                                     cfg.max_queue_total)
        self._execu = ChunkExecutor(
            policy=RetryPolicy(attempts=cfg.retry_attempts, base_s=0.2,
                               max_s=5.0),
            cpu_fallback=cfg.cpu_fallback, log=self.log)
        self._kinds: dict[str, Callable[[dict], Any]] = {}
        # swarmscope (docs/OBSERVABILITY.md): a PRIVATE registry per
        # service — the soak runs a crashed service and its reference
        # oracle in one process, and their ledgers must not mix.
        # Created before _recover(): recovery re-admissions and replayed
        # terminal results count like live traffic. (And before _lock,
        # which feeds its hold/wait histograms into it.)
        self.telemetry = MetricsRegistry()
        self._jobs: dict[str, _Job] = {}           # guarded-by: _lock
        self._done_prior: dict[str, Result] = {}   # guarded-by: _lock
        self._lock = OrderedLock("serve.service", registry=self.telemetry)
        self._stop = threading.Event()
        self._draining = threading.Event()
        self._closed = False          # guarded-by: _lock
        self._round = 0
        self.stats = {"accepted": 0, "completed": 0, "rejected": 0,
                      "preempted": 0, "timed_out": 0, "failed": 0,
                      "resumed": 0, "chunks": 0, "rounds": 0,
                      "workers": max(1, cfg.workers), "failovers": 0,
                      "requeued": 0, "poisoned": 0, "cancelled": 0}
        self._journal = Path(cfg.journal_dir) if cfg.journal_dir else None
        self._ckpt_dir = (self._journal / "ckpt"
                          if self._journal is not None else None)
        # swarmtrace: the lifecycle stream shares the journal's
        # events.log with the PR-8 worker ledger (one torn-tail-tolerant
        # frame log, one reader), and the span ring is armed to flush on
        # SIGTERM/atexit/worker-death so the last ~N spans survive a
        # crash (`telemetry.spans.install_crash_dump`)
        self._trace: Optional[LifecycleLog] = None
        self._span_dump = None
        # incarnation fence (process mode): checked before every journal
        # write, cached between FENCE_CHECK_S re-stats so the hot path
        # pays one monotonic read, not a stat per frame
        self._fence_path = (self._journal / FENCE_NAME
                            if self._journal is not None else None)
        self._fence_next = 0.0
        self._fence_lost = False
        # the scrape surface reports the process identity alongside the
        # fleet gauges — `watch --follow` tells a RESPAWNED worker
        # process (new pid + incarnation) from a reconnect of the old
        # one (same pid + incarnation) by exactly these two
        self.telemetry.gauge("serve_pid").set(os.getpid())
        self.telemetry.gauge("serve_incarnation").set(cfg.incarnation)
        if self._journal is not None:
            self._journal.mkdir(parents=True, exist_ok=True)
            if not self._fence_ok():
                raise RuntimeError(
                    f"journal {self._journal} is fenced by a newer "
                    f"incarnation than {cfg.incarnation} — refusing to "
                    "recover a journal this process no longer owns")
            self._trace = LifecycleLog(self._journal / "events.log",
                                       log=self.log)
            self._span_dump = install_crash_dump(
                self.telemetry.recorder,
                self._journal / "spans_dump.jsonl", log=self.log)
            self._recover()
        # the worker fleet (serve.workers): N supervised device workers
        # with heartbeat/lease failover — worker death is routine, not
        # a service outage
        from aclswarm_tpu.serve.workers import WorkerPool
        self._pool = WorkerPool(self, cfg)
        # swarmwatch (docs/OBSERVABILITY.md §swarmwatch): memory +
        # judgment over the registry. Built AFTER the pool so the probe
        # can read fleet liveness; the alert emit rides the journal's
        # events.log (fleet-scope `alert` records, always journaled —
        # _LEDGER_EVENTS), so the live surface and the postmortem
        # surface share one stream.
        self.watch = None
        if cfg.watch:
            from aclswarm_tpu.telemetry.slo import SwarmWatch, default_slos
            specs = (list(cfg.slos) if cfg.slos is not None
                     else default_slos(max_queue_total=cfg.max_queue_total))
            self.watch = SwarmWatch(
                self.telemetry, specs,
                interval_s=cfg.watch_interval_s,
                capacity=cfg.watch_capacity,
                persist_path=(self._journal / "timeseries.log"
                              if self._journal is not None else None),
                emit=self._emit_alert, probe=self._watch_probe,
                log=self.log)
        if start:
            self.start()

    # ------------------------------------------------------------ clients

    def register(self, kind: str, fn: Callable[[dict], Any]) -> None:
        """Install an extension request kind (``fn(params) -> value``,
        executed on the worker under the retry/degrade executor).
        `bench.py` and the suites register their measurements here."""
        if kind in BUILTIN_KINDS:
            raise ValueError(f"kind {kind!r} is built in")
        self._kinds[kind] = fn

    def submit(self, kind: str, params: dict, *, tenant: str = "default",
               request_id: Optional[str] = None,
               deadline_s: Optional[float] = None,
               trace_id: Optional[str] = None) -> Ticket:
        """Admit one request. Returns the `Ticket` the service now owes
        a terminal result on; raises `RejectedError` (backpressure /
        shutdown) or `ValueError` (malformed request) WITHOUT accepting.

        ``request_id`` is the idempotency key: re-submitting an id the
        service has seen (this process, or this journal — including
        already-terminal requests from before a crash) returns the
        existing ticket and never enqueues duplicate work.

        ``trace_id`` is the swarmtrace causal id: callers that already
        hold one (the wire client mints at its end of the pipe) pass it
        through; otherwise one is minted here — either way the id rides
        the journal acceptance frame, every checkpoint manifest, every
        lifecycle event, and the terminal `Result`."""
        if deadline_s is None:
            deadline_s = self.cfg.default_deadline_s
        rid = request_id or uuid.uuid4().hex[:12]
        req = Request(kind=kind, params=params, tenant=tenant,
                      request_id=rid, deadline_s=deadline_s,
                      t_submit=time.time(),
                      trace_id=trace_id or mint_trace_id())
        with self._lock:
            # idempotency first: re-submitting a known id must return
            # the existing ticket even while the service is draining
            if request_id is not None:
                if request_id in self._jobs:
                    return self._jobs[request_id].ticket
                prior = self._done_prior.get(request_id)
                if prior is not None:
                    t = Ticket(request_id)
                    t._resolve(prior)
                    return t
            if self._stop.is_set() or self._draining.is_set():
                raise RejectedError(E_SHUTDOWN, 0.0)
            job = self._make_job(req)      # validates; ValueError = refuse
            # the id reservation shares the duplicate check's lock: two
            # racing submits with one request_id cannot both build jobs
            # — the loser attaches to THIS ticket above
            self._jobs[rid] = job
        journaled = False      # acceptance events on disk -> the error
        #                        path owes the timeline a terminal record
        try:
            # caps-then-durable-then-prepped-then-runnable: admission
            # HOLDS a caps slot (picker-invisible) before the journal
            # frame is written, so rejected work is never journaled —
            # not even transiently (a crash between frame and rejection
            # cannot resurrect refused work) — the frame (the
            # acceptance promise) is durable before a worker that might
            # crash mid-chunk can run the job, and (with staging) the
            # request's batch-layout row is BUILT here at submit so
            # round-time pack is an index shuffle, never problem
            # construction (serve.staging; docs/SERVICE.md)
            self._adm.admit(job, hold=True)
            if self._journal is not None:
                if not self._fence_ok():
                    # a fenced process must not take NEW acceptance
                    # promises: its journal frames would be invisible
                    # to the incarnation that owns the dir now, which
                    # is exactly a silent loss
                    raise RejectedError(E_SHUTDOWN, 0.0)
                _write_frame(
                    self._req_path(rid), {"params": params},
                    ckptlib.make_manifest(
                        "serve_req", ckptlib.config_hash(params), chunk=0,
                        request_id=rid, tenant=tenant, req_kind=kind,
                        deadline_s=deadline_s, t_submit=req.t_submit,
                        incarnation=self.cfg.incarnation,
                        trace_id=req.trace_id))
                # the acceptance events land BEFORE the job becomes
                # pickable: a fast worker's `batched` record must never
                # precede `submitted` in the causal file order
                journaled = True
                self._journal_event("submitted", job, kind=kind,
                                    tenant=tenant, deadline_s=deadline_s,
                                    t_submit=req.t_submit)
                self._journal_event("admitted", job,
                                    queue_depth=self._adm.pending())
            if self.cfg.staging and job.spec is not None:
                # submit-time prep: the initial SimState row + problem
                # pieces, cached per shape. A prep failure is NOT an
                # admission failure — the worker-side build path
                # (`_ensure_state`) keeps legacy failure semantics for
                # pathological params, so fall back silently here.
                try:
                    state, form, cgains, sparams, cfg2 = \
                        _rollout_problem(job.spec)
                    job.state = state
                    job._problem = (form, cgains, sparams, cfg2)
                except Exception:       # noqa: BLE001 — worker rebuilds
                    job.state = None
                    job._problem = None
            self._adm.release(job)
        except BaseException as e:
            rejected = isinstance(e, RejectedError)
            with self._lock:
                self._jobs.pop(rid, None)
                if rejected:
                    self.stats["rejected"] += 1
                # atomic terminal reservation (shared with _finish): if
                # release() raised after the job turned pickable, a
                # worker may race this cleanup — first claimant wins
                already = job.finished
                job.finished = True
            if rejected:
                # admission ledger + the backpressure hints handed out
                self.telemetry.counter("serve_rejected_total").inc()
                self.telemetry.histogram("serve_retry_after_s").observe(
                    e.retry_after_s)
            self._adm.cancel(job)
            self._sample_queue()
            if journaled and not already:
                # the acceptance events are already on disk: without a
                # terminal record this request reconstructs as a
                # journaled loss. Close the timeline BEFORE the frame
                # unlink below retracts the acceptance promise.
                self._journal_event(
                    "resolved", job, status=FAILED, chunks=0,
                    error_code=E_QUEUE_FULL if rejected else E_EXECUTION)
            if self._journal is not None and not self._fence_lost:
                # fenced submits raised BEFORE writing their frame —
                # unlinking here would delete a frame the successor
                # incarnation may have journaled under the same rid
                self._req_path(rid).unlink(missing_ok=True)
            # a duplicate submit that attached during the reservation
            # window holds this ticket: resolve it so it can never
            # dangle (the primary caller sees the raised error)
            if not already:
                job.ticket._resolve(Result(
                    request_id=rid, status=FAILED,
                    error=ServeError(
                        E_QUEUE_FULL if rejected else E_EXECUTION,
                        f"submit failed before acceptance: {e}")))
            raise
        with self._lock:
            self.stats["accepted"] += 1
            orphaned = self._closed
        self.telemetry.counter("serve_accepted_total").inc()
        self._sample_queue()   # depth is fresh the moment work exists —
        #                        not at some future chunk boundary
        if orphaned:
            # close() raced this submit and its cleanup sweep already
            # ran: nobody is left to schedule the job, so honor the
            # acceptance promise HERE with a structured error instead of
            # leaving a ticket that never resolves (the frame stays
            # un-done for a later recovery)
            self._finish(job, FAILED,
                         error=ServeError(E_SHUTDOWN,
                                          "service closed while this "
                                          "request was being accepted"),
                         journal=False)
        return job.ticket

    def result(self, ticket: Ticket, timeout: Optional[float] = None
               ) -> Result:
        return ticket.result(timeout)

    def start(self) -> None:
        """Launch the worker fleet (no-op if already started). Split
        from __init__ for admission-control tests and staged recovery
        drills (``start=False``)."""
        self._pool.start()
        if self.watch is not None:
            # after the fleet: the first sample must see live
            # worker_up gauges, not a pre-spawn fleet of zeros
            self.watch.start()

    @property
    def alive(self) -> bool:
        """True while the service can still make progress: at least one
        worker thread is alive, OR the supervisor is (it can respawn a
        dead worker after its rejoin backoff — a worker death is a
        FAILOVER, not an outage). False after a clean exit, or once the
        whole fleet is circuit-open/dead — clients waiting without a
        timeout should poll this instead of blocking forever on a
        ticket nobody will resolve (journal recovery is how such
        tickets get honored)."""
        return self._pool.any_alive()

    def close(self, drain: bool = True, timeout: float = 120.0) -> None:
        """Stop the service. ``drain=True`` (the clean shutdown): refuse
        new work, run every accepted request to a terminal result, then
        stop once all tenants are idle. ``drain=False``: stop after the
        current round; still-queued requests resolve with a structured
        ``service_shutdown`` error (their journal frames stay un-done,
        so a later recovery can still honor them).

        A drain that cannot finish within ``timeout`` is NOT silent: it
        is logged loudly (with the count of requests it abandons) and
        the abandoned tickets resolve with a structured error naming
        the drain timeout — the promise is downgraded audibly, never
        dropped."""
        self._draining.set()
        if not drain:
            self._stop.set()
        self._adm.wake()
        drain_timed_out = False
        if self._pool.started and self._pool.any_alive():
            self._pool.join(timeout)
            drain_timed_out = drain and self._pool.any_alive()
        self._stop.set()
        if drain_timed_out:
            err = ServeError(
                E_SHUTDOWN,
                f"close(drain=True) abandoned the drain after {timeout:g}"
                " s with this request still in flight (journal frame "
                "stays un-done for recovery)")
        else:
            err = ServeError(E_SHUTDOWN, "service closed before this "
                                         "request was scheduled")
        with self._lock:
            # ordering handshake with submit(): a submit that inserts
            # its job after this flag flips resolves it itself
            self._closed = True
            pending = [j for j in self._jobs.values() if not j.finished]
        if drain_timed_out:
            self.log.error(
                "close(drain=True): worker still busy after the %g s "
                "join — resolving %d still-pending request(s) with a "
                "structured %s error; results the worker still produces "
                "are discarded by the finish once-guard",
                timeout, len(pending), E_SHUTDOWN)
        for job in pending:
            self._finish(job, FAILED, error=err, journal=False)
        if self.watch is not None:
            # before the trace log closes: the sampler's final tick
            # covers the shutdown edge, and any last alert transition
            # still lands in events.log
            self.watch.stop()
        if self._span_dump is not None:
            # clean close: final flush, then disarm the atexit/SIGTERM
            # hooks so long-lived test processes don't accumulate them
            self._span_dump.dump("close")
            self._span_dump.uninstall()
        if self._trace is not None:
            self._trace.close()

    # --------------------------------------------------------- internals

    def _make_job(self, req: Request) -> _Job:
        if req.kind in ("rollout", "scenario"):
            spec = _parse_rollout(
                _scenario_to_rollout(req.params)
                if req.kind == "scenario" else req.params)
            job = _Job(req=req, ticket=Ticket(req.request_id),
                       bucket=_bucket_from_spec(spec),
                       spec=spec, chunks_total=spec.n_chunks)
        elif req.kind in BUILTIN_KINDS or req.kind in self._kinds:
            job = _Job(req=req, ticket=Ticket(req.request_id),
                       bucket=("single", req.kind), chunks_total=1)
        else:
            raise ValueError(f"unknown request kind {req.kind!r} "
                             f"(builtin: {BUILTIN_KINDS}, registered: "
                             f"{sorted(self._kinds)})")
        job.t_accept = time.monotonic()
        return job

    def _req_path(self, rid: str) -> Path:
        assert self._journal is not None
        return self._journal / f"req_{rid}.req"

    def _done_path(self, rid: str) -> Path:
        assert self._journal is not None
        return self._journal / f"req_{rid}.done"

    # ------------------------------------------------- incarnation fence

    FENCE_CHECK_S = 0.05    # max fence-observation latency (re-stat gap)

    def _fence_ok(self) -> bool:
        """True while this process still owns its journal. Process mode:
        a successor incarnation fences the shared per-slot journal dir
        (`write_fence`) before recovering it; this predecessor — a
        zombie that missed its lease but never exited — sees the fence
        within ``FENCE_CHECK_S`` and every subsequent journal write
        no-ops LOUDLY. Stamped frames plus this check are what make
        "declare dead on connection death" safe without waiting for
        the process to actually die. Thread mode never writes a fence,
        so the check stays a cached no-op."""
        if self._fence_path is None:
            return True
        if self._fence_lost:
            return False
        now = time.monotonic()
        if now < self._fence_next:
            return True
        self._fence_next = now + self.FENCE_CHECK_S
        fence = read_fence(self._journal)
        if fence is not None and fence > int(self.cfg.incarnation):
            self._fence_lost = True
            self.telemetry.counter("serve_fenced_total").inc()
            self.log.error(
                "journal FENCE: incarnation %d owns %s now (this "
                "process is incarnation %d) — every further journal "
                "write from this process is a no-op",
                fence, self._journal, self.cfg.incarnation)
            return False
        return True

    # ------------------------------------------------------- worker rounds
    #
    # The worker LOOP lives in `serve.workers.WorkerPool` (pick, exit
    # conditions, heartbeat, InjectedCrash handling, in-flight
    # bookkeeping); the round EXECUTION lives here with the rest of the
    # request state machine. Every per-job mutation is guarded by the
    # (job, epoch-at-pick) pairs the pool hands in: a fenced zombie
    # worker whose jobs were failed over observes a bumped epoch and
    # touches nothing.
    #
    # A round is SPLIT into two phases so the worker loop can
    # double-buffer (docs/SERVICE.md §scheduling): `_round_start` gates
    # + packs + dispatches (async — the device starts immediately), and
    # `_round_finish` syncs + unpacks + resolves. With
    # ``cfg.pipeline=True`` the pool starts round k+1 before finishing
    # round k, so the host's pack/resolve work overlaps the device's
    # chunk compute; otherwise the phases run back to back (the PR-9
    # schedule).

    def _round_start(self, pairs: list, worker,
                     busy_ids: frozenset = frozenset()
                     ) -> Optional["_PendingRound"]:
        """Phase 1 of one scheduler round: crash hooks, then the
        bucket-appropriate execution. Returns a `_PendingRound` when
        the round's device work was dispatched asynchronously (staged
        rollout buckets) — the caller owes a `_round_finish`. Returns
        None when the round already completed (single-shot kinds, the
        legacy pack-at-round-time path, an all-gated-out batch, or
        ``pipeline=False``). ``busy_ids`` are ids of jobs mid-flight in
        the caller's still-pending round: their staging rows are
        neither consistent nor evictable until that round resolves."""
        jobs = [j for j, _ in pairs]
        with self._lock:
            self._round += 1
            grnd = self._round
            self.stats["rounds"] = self._round
        # the scripted-crash hooks: the process-level site ("serve",
        # global round — the PR-6 SIGKILL drills) and the worker-
        # targeted site ("serve.w{slot}", the slot's cumulative round —
        # the single-worker failover drills). Both fire HERE, with the
        # batch picked and registered in-flight: exactly what a killed
        # worker leaves behind.
        maybe_crash(CRASH_SITE, grnd)
        from aclswarm_tpu.serve.workers import WORKER_SITE
        maybe_crash(WORKER_SITE.format(slot=worker.slot), worker.round)
        if jobs[0].bucket[0] != "rollout" or not self.cfg.staging:
            with self.telemetry.span("serve.round", round=grnd,
                                     worker=worker.slot,
                                     bucket=str(jobs[0].bucket[0]),
                                     batch=len(jobs)):
                if jobs[0].bucket[0] == "rollout":
                    self._rollout_round(pairs, worker)
                else:
                    for job, epoch in pairs:
                        self._single(job, epoch, worker)
            return None
        pending = self._rollout_round_start(pairs, worker, grnd,
                                            busy_ids)
        if pending is not None and not self.cfg.pipeline:
            self._round_finish(pending, worker)
            return None
        return pending

    def _fail_round(self, pairs: list, exc: BaseException) -> None:
        """A round-level bug must not wedge the service: every job of
        the round terminates with structured evidence."""
        err = ServeError(E_EXECUTION,
                         f"{type(exc).__name__}: {exc}",
                         detail=self._execu.row_fields() or None)
        for job, _ in pairs:
            if not job.ticket.done:
                self._finish(job, FAILED, error=err)

    def _stale(self, job: _Job, epoch: int) -> bool:
        """True when this residency no longer owns the job (finished by
        a racing path, or failed over to another worker)."""
        with self._lock:
            return job.finished or job.epoch != epoch

    # -------------------------------------------------- rollout batching

    def _ensure_state(self, job: _Job, epoch: Optional[int] = None
                      ) -> None:
        """Materialize the resident carry: fresh problem at chunk 0, or
        a template-validated restore of the preemption/crash checkpoint
        (THE checkpoint-backed path — restore goes through the codec
        even for in-memory preemption)."""
        if job.state is not None:
            return
        state, form, cgains, sparams, cfg = _rollout_problem(job.spec)
        job._problem = (form, cgains, sparams, cfg)
        frame = None
        if job._ckpt_bytes is not None:
            # NOT consumed: the frame stays until a newer checkpoint
            # overwrites it (or the job terminates). A staged job that
            # is failed over again BEFORE its next chunk resolves has
            # no resident state to serialize — this frame is then still
            # the authoritative state@chunks_done, and dropping it here
            # would turn that second failover into a silent restart
            # (caught once, the hard way: the exoneration drill's
            # double-kill).
            frame = ckptlib.loads(job._ckpt_bytes,
                                  f"<mem:{job.req.request_id}>")
        elif self._ckpt_dir is not None:
            path = ckptlib.latest_checkpoint(self._ckpt_dir,
                                             self._stem(job))
            if path is not None:
                frame = ckptlib.load_checkpoint(
                    path, expected=ckptlib.expected_manifest(
                        "serve_rollout",
                        ckptlib.config_hash(job.req.params),
                        request_id=job.req.request_id))
        if frame is not None:
            payload, man = frame
            job.state = ckptlib.restore_tree(state, payload["state"],
                                             path=self._stem(job),
                                             what="SimState")
            job.chunks_done = int(man["chunk"])
            job.crc = int(payload["crc"])
            job.chunk_digests = [int(d) for d in payload["chunk_digests"]]
            job.preemptions = int(payload["preemptions"])
            if epoch is not None:
                self._journal_event_owned("resumed", job, epoch,
                                          from_chunk=job.chunks_done,
                                          preemptions=job.preemptions)
            else:
                self._journal_event("resumed", job,
                                    from_chunk=job.chunks_done,
                                    preemptions=job.preemptions)
        else:
            if job.chunks_done > 0 and not job.finished:
                # a mid-flight job with NO checkpoint anywhere must
                # never silently restart from tick 0 under a stale
                # chunk counter — that is digest corruption, not
                # recovery. Fail the round loudly instead (the job
                # terminates with structured evidence via _fail_round).
                # A job that just RACED to terminal is exempt: its
                # fresh state is never read (epoch/finished guards).
                raise RuntimeError(
                    f"request {job.req.request_id} is at chunk "
                    f"{job.chunks_done}/{job.chunks_total} but no "
                    "checkpoint frame exists (memory or disk) — "
                    "refusing a silent restart-from-zero")
            job.state = state

    def _stem(self, job: _Job) -> str:
        return f"req_{job.req.request_id}"

    def _checkpoint(self, job: _Job, to_disk: bool, state=None) -> None:
        payload = {"state": ckptlib.tree_arrays(
                       job.state if state is None else state),
                   "crc": int(job.crc),
                   "chunk_digests": [int(d) for d in job.chunk_digests],
                   "preemptions": int(job.preemptions)}
        man = ckptlib.make_manifest(
            "serve_rollout", ckptlib.config_hash(job.req.params),
            chunk=job.chunks_done, request_id=job.req.request_id,
            trace_id=job.req.trace_id)
        if to_disk:
            assert self._ckpt_dir is not None
            if not self._fence_ok():
                # a zombie's checkpoint would race the successor's
                # resume of the same request — skip the disk write
                # (the in-memory copy below is process-local and safe)
                self.telemetry.counter("serve_fenced_writes_total").inc()
            else:
                ckptlib.write_checkpoint(self._ckpt_dir, self._stem(job),
                                         payload, man)
        else:
            job._ckpt_bytes = ckptlib.dumps(payload, man)
        self._journal_event("checkpointed", job, chunk=job.chunks_done,
                            durable=bool(to_disk))

    def _rollout_round(self, pairs: list, worker) -> None:
        """One chunk for one shape bucket: deadline/cancel gate ->
        restore -> pad to the power-of-two batch -> ONE `batched_rollout`
        launch -> unstack, stream, checkpoint, then
        complete/preempt/requeue. Every mutation is epoch-guarded: a
        job failed over mid-round (this worker fenced as a zombie) is
        skipped entirely — the new owner's restored state is
        authoritative."""
        import jax
        import jax.numpy as jnp

        from aclswarm_tpu import sim

        # swarmtrace stage spans: the serve.round parent is split into
        # pack/stack/dispatch/device-sync/unpack/resolve children, each
        # auto-feeding its span_serve.round.<stage>_s histogram — the
        # per-stage breakdown `benchmarks/serve_latency_breakdown.py`
        # commits (docs/OBSERVABILITY.md §swarmtrace)
        span = self.telemetry.span
        wat = {"worker": worker.slot}
        with span("serve.round.pack", **wat):
            live, epochs = [], {}
            for job, epoch in pairs:
                if self._stale(job, epoch):
                    continue
                if self._expired(job):
                    self._timeout(job)
                elif job.cancelled is not None:
                    self._cancel_at_boundary(job)
                else:
                    live.append(job)
                    epochs[id(job)] = epoch
            for job in live:
                self._journal_event_owned(
                    "batched", job, epochs[id(job)], worker=worker.slot,
                    round=worker.round, batch=len(live),
                    bucket=str(job.bucket[0]), chunk=job.chunks_done)
                self._ensure_state(job, epochs[id(job)])
                job.status = RUNNING
                if job.t_first_run is None:
                    job.t_first_run = time.monotonic()
            if live and worker.device is not None:
                # multi-device host: pin each job's carry to this
                # worker's mesh-slice lead device BEFORE stacking — the
                # compiled launch follows its operands, so N workers
                # genuinely run N device streams. Per-job (not
                # post-stack) because a batch can mix residencies: a
                # freshly-migrated job's restored state lives on the
                # default device while its batch-mate's carry lives on
                # this worker's — stacking across devices is an error,
                # not a transfer (CPU single-device fallback: device is
                # None, no-op)
                for job in live:
                    job.state = jax.device_put(job.state, worker.device)
        if not live:
            return
        with span("serve.round.stack", **wat):
            form, cgains, sparams, cfg = live[0]._problem
            chunk = live[0].spec.chunk_ticks
            B = len(live)
            P = stagelib.pow2(B)
            idx = list(range(B)) + [0] * (P - B)   # pow-2 pad: bounded
            bstate = jax.tree.map(
                lambda *xs: jnp.stack(xs), *[live[i].state for i in idx])
            bform = jax.tree.map(
                lambda *xs: jnp.stack(xs),
                *[live[i]._problem[0] for i in idx])
            if worker.device is not None:
                bform = jax.device_put(bform, worker.device)
        t0 = time.monotonic()
        with span("serve.round.dispatch", **wat):
            bstate, metrics = self._execu.run(
                lambda: sim.batched_rollout(bstate, bform, cgains, sparams,
                                            cfg, chunk, None, 0),
                stage=f"serve:w{worker.slot}:round{self._round}")
        with span("serve.round.device_sync", **wat):
            q_all = np.asarray(metrics.q)      # (T, P, n, 3) — the host sync
        with span("serve.round.unpack", **wat):
            done_live = []
            for i, job in enumerate(live):
                qb = np.ascontiguousarray(q_all[:, i])
                # stale-check AND mutations share one lock hold: a
                # lease-lapse failover landing between an unlocked check
                # and these writes would let this (now-zombie) residency
                # repopulate job.state after the supervisor nulled it —
                # the next residency would then skip its restore and run
                # with _problem=None
                with self._lock:
                    if job.finished or job.epoch != epochs[id(job)]:
                        continue       # failed over mid-launch: zombie
                    job.state = jax.tree.map(lambda x: x[i], bstate)
                    job.crc = zlib.crc32(qb.tobytes(), job.crc) & 0xFFFFFFFF
                    job.chunk_digests.append(job.crc)
                    job.chunks_done += 1
                    job.run_chunks += 1
                    if job.suspect:
                        # EXONERATED: it survived a (solo, by the
                        # quarantine pick rule) chunk — the kill it
                        # witnessed was not its doing, and the kill ledger
                        # resets with it so only a job that KEEPS killing
                        # workers can ever accumulate to the poison bound
                        job.suspect = False
                        job.solo_kills = 0
                        job.excluded_workers.clear()
                    done_live.append(job)
                    ev = ChunkEvent(
                        job.req.request_id, job.chunks_done - 1,
                        {"chunk": job.chunks_done - 1,
                         "tick_end": job.chunks_done * chunk,
                         "digest": job.crc,
                         "batch": B,
                         "worker": worker.slot,
                         "trace_id": job.req.trace_id})
                    # the chunk record lands under the same lock hold as
                    # the digest update: a concurrent failover can never
                    # journal a migration of this chunk BEFORE the chunk
                    # itself exists in the stream (causal file order)
                    self._journal_event(
                        "chunk", job, k=job.chunks_done - 1,
                        digest=int(job.crc), worker=worker.slot,
                        round=worker.round,
                        tick_end=job.chunks_done * chunk)
                job.ticket._push(ev)
            with self._lock:
                self.stats["chunks"] += len(done_live)
            dev_s = time.monotonic() - t0
            self._adm.note_service(dev_s / max(1, B))
            self._attribute_device(done_live, dev_s)
            self._sample_boundary(len(done_live), worker)

        with span("serve.round.resolve", **wat):
            self._resolve_round(done_live, epochs, chunk)

    def _resolve_round(self, done_live: list, epochs: dict,
                       chunk: int) -> None:
        """Post-chunk request state machine: complete / deadline /
        cancel / preempt / checkpoint / requeue, per job."""
        for job in done_live:
            # snapshot under the lock: a concurrent failover (fenced
            # zombie scenario) may null job.state the instant after —
            # this residency then finishes/checkpoints from ITS
            # consistent snapshot, and the once-guard/epoch checks
            # arbitrate who wins
            with self._lock:
                if job.finished or job.epoch != epochs[id(job)]:
                    continue
                state_ref = job.state
            if job.chunks_done >= job.chunks_total:
                q_final = np.asarray(state_ref.swarm.q)
                self._finish(job, COMPLETED, value={
                    "q": q_final,
                    "ticks": job.chunks_done * chunk,
                    "digest": int(job.crc),
                    "chunk_digests": [int(d) for d in job.chunk_digests]})
                if self._ckpt_dir is not None:
                    ckptlib.clear_checkpoints(self._ckpt_dir,
                                              self._stem(job))
                continue
            if self._expired(job):
                self._timeout(job)
                continue
            if job.cancelled is not None:
                self._cancel_at_boundary(job)
                continue
            # checkpoint-backed preemption: a job past its quantum with
            # other work waiting is evicted through the codec; the next
            # residency restores it exactly. The count increments BEFORE
            # the frame is written — the frame is the job's authoritative
            # record across restores.
            preempt = (job.run_chunks >= self.cfg.quantum_chunks
                       and self._adm.pending_excluding(job) > 0)
            if preempt:
                job.preemptions += 1
                with self._lock:
                    self.stats["preempted"] += 1
                self.telemetry.counter("serve_preempted_total").inc()
                self._journal_event("preempted", job,
                                    chunk=job.chunks_done,
                                    run_chunks=job.run_chunks)
            # durability checkpoint every chunk when journaled: a
            # SIGKILL between rounds costs at most one chunk of work
            # (from the snapshot — job.state may be nulled by a
            # concurrent failover)
            if self._ckpt_dir is not None:
                self._checkpoint(job, to_disk=True, state=state_ref)
            elif preempt:
                self._checkpoint(job, to_disk=False, state=state_ref)
            # epoch guard AND the enqueue itself share one lock hold:
            # the failover supervisor serializes against this exact
            # section (its contains-check + requeue also run under
            # _lock), so a job can never be enqueued twice by a
            # boundary requeue racing a lease-lapse failover
            with self._lock:
                if job.finished or job.epoch != epochs[id(job)]:
                    continue           # failed over while checkpointing
                if preempt:
                    job.state = None
                    job._problem = None
                    job.status = PREEMPTED
                    job.run_chunks = 0
                else:
                    job.status = QUEUED
                job.worker = None
                # journaled before the job becomes pickable (same lock
                # hold): the next residency's `batched` record must
                # follow this `queued` in the causal file order
                self._journal_event(
                    "queued", job,
                    reason="preempt" if preempt else "boundary")
                self._adm.requeue(job)

    # ------------------------------------- staged rounds (serve.staging)
    #
    # The device-bound round (docs/SERVICE.md §scheduling): requests
    # were prepped into rows at submit; pack scatters newcomers into
    # the bucket's persistent staging store (donated writes), the
    # batch is ONE compiled gather of the live slots, the rollout +
    # batched unpack + scatter-back all dispatch asynchronously, and
    # `_round_finish` blocks exactly once (`device_get` of the
    # compacted result pytree). Staging-store mutations happen ONLY on
    # the owning worker thread, under `_lock`, after re-checking the
    # fence flag — the supervisor reads rows under the same lock after
    # fencing, so donated buffers are never read (the JC005 contract,
    # enforced at runtime by this protocol and statically by jaxcheck).

    def _rollout_round_start(self, pairs: list, worker, grnd: int,
                             busy_ids: frozenset = frozenset()
                             ) -> Optional[_PendingRound]:
        import jax

        from aclswarm_tpu import sim

        span = self.telemetry.span
        wat = {"worker": worker.slot}
        attrs = {"round": grnd, "worker": worker.slot,
                 "bucket": "rollout", "batch": len(pairs)}
        t_phase = time.perf_counter()
        ok = False
        try:
            with span("serve.round.pack", **wat):
                live, epochs = [], {}
                for job, epoch in pairs:
                    if self._stale(job, epoch):
                        continue
                    if self._expired(job):
                        self._timeout(job)
                    elif job.cancelled is not None:
                        self._cancel_at_boundary(job)
                    else:
                        live.append(job)
                        epochs[id(job)] = epoch
                if not live:
                    return None
                st = worker.staging.get(live[0].bucket)
                if st is None:
                    st = worker.staging[live[0].bucket] = \
                        stagelib.BucketStaging(device=worker.device)
                for job in live:
                    self._journal_event_owned(
                        "batched", job, epochs[id(job)],
                        worker=worker.slot, round=worker.round,
                        batch=len(live), bucket=str(job.bucket[0]),
                        chunk=job.chunks_done)
                    sref = job.staged
                    if sref is not None and sref[0] is not st:
                        # stranded in a dead incarnation's staging
                        # (boundary-queued at its death, so never
                        # failed over): its row is consistent — read
                        # it out under the lock, where no fenced owner
                        # can concurrently donate the old store
                        with self._lock:
                            if worker.fenced:
                                raise _Fenced()
                            # re-read UNDER the lock: a failover or a
                            # terminal sweep may have nulled job.staged
                            # since the unlocked check above
                            sref = job.staged
                            if sref is not None and sref[0] is not st:
                                old, slot = sref
                                row_s, row_f = stagelib.take_row(
                                    old.store, stagelib.i32(slot))
                                job.state = row_s
                                job._problem = \
                                    (row_f,) + tuple(old.shared)
                                # the materialized row REPLACES the
                                # batch-shaped shadow (same chunk, one
                                # row pinned instead of a whole round
                                # output) — never cleared: the staging
                                # join below nulls job.state, and an
                                # unjournaled mid-flight failover
                                # after that must still find
                                # state@chunks_done somewhere
                                job._shadow = (row_s, None)
                                if old.slots[slot] is job:
                                    old.slots[slot] = None
                                job.staged = None
                    if job.staged is None and job.state is None:
                        self._ensure_state(job, epochs[id(job)])
                    job.status = RUNNING
                    if job.t_first_run is None:
                        job.t_first_run = time.monotonic()
                # staging admission: write every newcomer's row into
                # the store — ONE donated compiled call each, under the
                # lock + fence check (the staging concurrency contract).
                # Capacity is FIXED (2x the padded batch: one round in
                # flight + one being packed — see BucketStaging): a
                # full store EVICTS a non-busy resident back to its
                # per-job row instead of growing, so the staging ops'
                # compiled shape set stays closed. live + busy <= cap
                # by construction, so a slot always frees up.
                with self._lock:
                    if worker.fenced:
                        raise _Fenced()
                    newcomers = [j for j in live
                                 if not (j.staged is not None
                                         and j.staged[0] is st)]
                    if newcomers and st.shared is None:
                        st.shared = tuple(newcomers[0]._problem[1:])
                    if newcomers:
                        if st.store is None:
                            st.create((newcomers[0].state,
                                       newcomers[0]._problem[0]),
                                      2 * stagelib.pow2(
                                          self.cfg.max_batch))
                        free = st.free_slots()
                        if len(free) < len(newcomers):
                            keep = {id(j) for j in live} | busy_ids
                            for slot, owner in enumerate(st.slots):
                                if len(free) >= len(newcomers):
                                    break
                                if owner is None or id(owner) in keep \
                                        or owner.finished:
                                    continue
                                # LRU-evict: the resident leaves the
                                # batch layout with its consistent row
                                # (it is neither live nor mid-flight)
                                # and re-stages on its next pick
                                row_s, row_f = stagelib.take_row(
                                    st.store, stagelib.i32(slot))
                                owner.state = row_s
                                owner._problem = \
                                    (row_f,) + tuple(st.shared)
                                # row-shadow, same reasoning as the
                                # stranded branch: replaces the
                                # batch-shaped shadow, never cleared
                                owner._shadow = (row_s, None)
                                owner.staged = None
                                st.slots[slot] = None
                                free.append(slot)
                        for job in newcomers:
                            slot = free.pop(0)
                            row = (job.state, job._problem[0])
                            if st.device is not None:
                                row = jax.device_put(row, st.device)
                            st.store = stagelib.write_row(
                                st.store, row, stagelib.i32(slot))
                            st.slots[slot] = job
                            job.staged = (st, slot)
                            job.state = None
                            job._problem = None
            with span("serve.round.stack", **wat):
                # the index shuffle: the round batch is one gather of
                # the live slots, padded to the same power-of-two
                # shapes the pack-at-round-time path compiled. Slot
                # reads happen UNDER the lock after a fence re-check:
                # a lease-lapse failover nulls job.staged, and it can
                # only have done so after fencing this worker — so an
                # unfenced read is consistent, and a fenced one aborts
                # instead of dereferencing a migrated job's None.
                B = len(live)
                P = stagelib.pow2(B)
                rows = {id(j): i for i, j in enumerate(live)}
                with self._lock:
                    if worker.fenced:
                        raise _Fenced()
                    slot_list = [j.staged[1] for j in live]
                    idx = slot_list + [slot_list[0]] * (P - B)
                    batch_state, batch_form = stagelib.gather_rows(
                        st.store, stagelib.i32(tuple(idx)))
            chunk = live[0].spec.chunk_ticks
            cgains, sparams, cfg = st.shared
            t0 = time.monotonic()
            with span("serve.round.dispatch", **wat):
                out, metrics = self._execu.run(
                    lambda: sim.batched_rollout(
                        batch_state, batch_form, cgains, sparams, cfg,
                        chunk, None, 0),
                    stage=f"serve:w{worker.slot}:round{grnd}")
                unpacked = stagelib.unpack_round(metrics.q, out.swarm.q)
                # scatter the output rows back into the (donated)
                # store: the staging buffer is reused in place, and the
                # next round's gather reads the updated rows — all
                # async, ordered by dataflow. The index vectors are
                # padded to P like the batch itself (pad entries
                # re-write row 0's slot with row 0's own values — a
                # bit-identical no-op) so scatter compiles per P, not
                # per live-count.
                with self._lock:
                    if worker.fenced:
                        raise _Fenced()
                    st.store = (stagelib.scatter_rows(
                        st.store[0], out, stagelib.i32(tuple(idx)),
                        stagelib.i32(tuple(range(B)) + (0,) * (P - B))),
                        st.store[1])
            ok = True
            return _PendingRound(pairs=pairs, jobs=live, epochs=epochs,
                                 rows=rows, out=out, unpacked=unpacked,
                                 staging=st, chunk=chunk, B=B, P=P,
                                 t0=t0, grnd=grnd, wround=worker.round,
                                 span_attrs=attrs,
                                 start_dur=time.perf_counter() - t_phase)
        finally:
            if not ok:
                # aborted/empty round: the span is just this phase
                self._emit_round_span(
                    time.perf_counter() - t_phase, attrs,
                    error=sys.exc_info()[0] is not None)

    def _round_finish(self, pending: _PendingRound, worker,
                      busy: int = 0) -> None:
        """Phase 2 of a staged round: ONE blocking `device_get` (the
        round's only host sync), per-job digest/stream bookkeeping,
        then the request state machine. ``busy`` is the number of jobs
        the worker already dispatched into the NEXT (overlapping)
        round — they count as waiting work for the preemption trigger,
        exactly as they would still have been queued at this point on
        the unpipelined schedule."""
        import jax

        span = self.telemetry.span
        wat = {"worker": worker.slot}
        t_phase = time.perf_counter()
        try:
            with span("serve.round.device_sync", **wat):
                host = jax.device_get(pending.unpacked)
            q_chunks = host["q_chunks"]
            with span("serve.round.unpack", **wat):
                done_live = []
                for job in pending.jobs:
                    bi = pending.rows[id(job)]
                    qb = q_chunks[bi]      # request-major: contiguous
                    # stale-check AND mutations share one lock hold (the
                    # same fenced-zombie reasoning as the legacy path)
                    with self._lock:
                        if job.finished \
                                or job.epoch != pending.epochs[id(job)]:
                            continue       # failed over mid-flight
                        if self._ckpt_dir is None:
                            # in-memory failover shadow: the staging
                            # row advances at DISPATCH of the next
                            # round, so an in-flight job's consistent
                            # state@chunks_done must live somewhere a
                            # migration can serialize. LAZY — just a
                            # (batch, row) reference; `_failover_job`
                            # materializes it with one take_row only
                            # if a migration actually happens
                            # (journaled services skip this: the
                            # per-chunk disk frame is the source)
                            job._shadow = (pending.out, bi)
                        job.crc = zlib.crc32(qb.tobytes(),
                                             job.crc) & 0xFFFFFFFF
                        job.chunk_digests.append(job.crc)
                        job.chunks_done += 1
                        job.run_chunks += 1
                        if job.suspect:
                            # EXONERATED (see the legacy path)
                            job.suspect = False
                            job.solo_kills = 0
                            job.excluded_workers.clear()
                        done_live.append(job)
                        ev = ChunkEvent(
                            job.req.request_id, job.chunks_done - 1,
                            {"chunk": job.chunks_done - 1,
                             "tick_end": job.chunks_done * pending.chunk,
                             "digest": job.crc,
                             "batch": pending.B,
                             "worker": worker.slot,
                             "trace_id": job.req.trace_id})
                        self._journal_event(
                            "chunk", job, k=job.chunks_done - 1,
                            digest=int(job.crc), worker=worker.slot,
                            round=pending.wround,
                            tick_end=job.chunks_done * pending.chunk)
                    job.ticket._push(ev)
                with self._lock:
                    self.stats["chunks"] += len(done_live)
                # the round's device span (dispatch -> sync complete):
                # one wall window, attributed across the occupied rows
                dev_s = time.monotonic() - pending.t0
                self._adm.note_service(dev_s / max(1, pending.B))
                self._attribute_device(done_live, dev_s)
                self._sample_boundary(len(done_live), worker)
            with span("serve.round.resolve", **wat):
                self._resolve_round_staged(pending, done_live,
                                           host["q_final"], busy)
        finally:
            self._emit_round_span(
                pending.start_dur + (time.perf_counter() - t_phase),
                pending.span_attrs,
                error=sys.exc_info()[0] is not None)

    def _emit_round_span(self, dur_s: float, attrs: dict,
                         error: bool = False) -> None:
        """Record one ``serve.round`` span of the given duration (the
        two active phases of a pipelined round — see `_PendingRound.
        start_dur`), feeding the same recorder + histogram the span
        context manager would."""
        from aclswarm_tpu.telemetry.spans import Span

        self.telemetry.recorder.record(Span(
            name="serve.round", t_wall=time.time(), dur_s=dur_s,
            attrs=dict(attrs, error=True) if error else dict(attrs)))
        self.telemetry.histogram("span_serve.round_s").observe(dur_s)

    def _resolve_round_staged(self, pending: _PendingRound,
                              done_live: list, q_final, busy: int
                              ) -> None:
        """Post-chunk request state machine for a staged round:
        complete / deadline / cancel / preempt / checkpoint / requeue.
        Durability checkpoints read from ONE batched `device_get` of
        the round's output (numpy row views), not per-leaf per-job
        device slices."""
        import jax

        chunk = pending.chunk
        host_state = None

        def host_row(bi):
            # lazy: only rounds that actually checkpoint pay the
            # transfer, and they pay it once for the whole batch
            nonlocal host_state
            if host_state is None:
                host_state = jax.device_get(pending.out)
            return jax.tree.map(lambda x: x[bi], host_state)

        for job in done_live:
            bi = pending.rows[id(job)]
            with self._lock:
                if job.finished or job.epoch != pending.epochs[id(job)]:
                    continue
            if job.chunks_done >= job.chunks_total:
                self._finish(job, COMPLETED, value={
                    "q": np.ascontiguousarray(q_final[bi]),
                    "ticks": job.chunks_done * chunk,
                    "digest": int(job.crc),
                    "chunk_digests": [int(d) for d in job.chunk_digests]})
                if self._ckpt_dir is not None:
                    ckptlib.clear_checkpoints(self._ckpt_dir,
                                              self._stem(job))
                continue
            if self._expired(job):
                self._timeout(job)
                continue
            if job.cancelled is not None:
                self._cancel_at_boundary(job)
                continue
            preempt = (job.run_chunks >= self.cfg.quantum_chunks
                       and (busy > 0
                            or self._adm.pending_excluding(job) > 0))
            if preempt:
                job.preemptions += 1
                with self._lock:
                    self.stats["preempted"] += 1
                self.telemetry.counter("serve_preempted_total").inc()
                self._journal_event("preempted", job,
                                    chunk=job.chunks_done,
                                    run_chunks=job.run_chunks)
            if self._ckpt_dir is not None:
                self._checkpoint(job, to_disk=True, state=host_row(bi))
            elif preempt:
                self._checkpoint(job, to_disk=False, state=host_row(bi))
            with self._lock:
                if job.finished or job.epoch != pending.epochs[id(job)]:
                    continue           # failed over while checkpointing
                if preempt:
                    self._free_slot(job)
                    job.state = None
                    job._problem = None
                    job._shadow = None   # the checkpoint frame just
                    #                      written supersedes it
                    job.status = PREEMPTED
                    job.run_chunks = 0
                else:
                    job.status = QUEUED
                job.worker = None
                self._journal_event(
                    "queued", job,
                    reason="preempt" if preempt else "boundary")
                self._adm.requeue(job)

    def _free_slot(self, job: _Job) -> None:
        """Release the job's staging-store row (caller holds ``_lock``).
        Idempotent; a no-op for never-staged jobs."""
        sref = job.staged
        if sref is not None:
            st, slot = sref
            if 0 <= slot < len(st.slots) and st.slots[slot] is job:
                st.slots[slot] = None
            job.staged = None

    # ---------------------------------------------------- single-shot work

    def _single(self, job: _Job, epoch: int, worker) -> None:
        """Non-chunked kinds: the only boundaries are start and finish,
        and the deadline is enforced at both (work that finishes past
        its deadline is discarded with a structured error — the client
        was promised the deadline, not a late answer)."""
        if self._stale(job, epoch):
            return
        if self._expired(job):
            self._timeout(job)
            return
        if job.cancelled is not None:
            self._cancel_at_boundary(job)
            return
        job.status = RUNNING
        job.t_first_run = time.monotonic()
        kind = job.req.kind
        self._journal_event_owned("batched", job, epoch,
                                  worker=worker.slot, round=worker.round,
                                  batch=1, bucket=str(job.bucket[0]))
        fn = {"assign": self._do_assign,
              "gains": self._do_gains,
              "stats": self._do_stats,
              "health": self._do_health}.get(kind) or self._kinds[kind]
        t0 = time.monotonic()
        value = self._execu.run(
            lambda: fn(job.req.params),
            stage=f"{kind}:{job.req.request_id}:w{worker.slot}")
        dev_s = time.monotonic() - t0
        self._adm.note_service(dev_s)
        self._attribute_device([job], dev_s)
        self._sample_boundary(1, worker)
        if self._stale(job, epoch):
            return                     # failed over mid-execution
        if self._expired(job):
            self._timeout(job, late=True)
            return
        self._finish(job, COMPLETED, value=value)

    @staticmethod
    def _do_assign(params: dict):
        import jax.numpy as jnp

        from aclswarm_tpu.assignment import sinkhorn
        # the package re-exports the lapjv FUNCTION under the module's
        # name; import the host solver directly
        from aclswarm_tpu.assignment.lapjv import solve_assignment_host

        n = int(params.get("n", 16))
        seed = int(params.get("seed", 0))
        rng = np.random.default_rng(seed)
        q = (np.asarray(params["q"], float) if "q" in params
             else rng.normal(size=(n, 3)) * 10)
        p = (np.asarray(params["p"], float) if "p" in params
             else rng.normal(size=(n, 3)) * 10)
        solver = params.get("solver", "sinkhorn")
        if solver == "lap":
            perm = solve_assignment_host(q, p)
        elif solver == "sinkhorn":
            dt = jnp.result_type(float)
            r = sinkhorn.sinkhorn_assign(
                jnp.asarray(q, dt), jnp.asarray(p, dt),
                n_iters=int(params.get("n_iters", 50)))
            perm = np.asarray(r.row_to_col)
        else:
            raise ValueError(f"unknown assign solver {solver!r}")
        return {"perm": np.asarray(perm, np.int64), "solver": solver}

    @staticmethod
    def _do_gains(params: dict):
        from aclswarm_tpu import gains as gainslib

        n = int(params.get("n", 6))
        seed = int(params.get("seed", 0))
        if "points" in params:
            pts = np.asarray(params["points"], float)
            adj = np.asarray(params["adjmat"], float)
        else:
            rng = np.random.default_rng(seed)
            ang = np.linspace(0, 2 * np.pi, n, endpoint=False)
            pts = np.stack([4 * np.cos(ang), 4 * np.sin(ang),
                            2.0 + 0.1 * rng.normal(size=n)], 1)
            adj = np.ones((n, n)) - np.eye(n)
        # ADMM warm start riding the request (the FaultSchedule idiom:
        # state crosses the wire as codec-plain params, so preemption /
        # migration replay keeps it). ``carry``: a previous response's
        # carry dict; ``warm``: truthy to bootstrap warm threading
        # without one. Neither present = the legacy stateless solve and
        # the legacy response shape, byte-identical.
        carry_in = params.get("carry")
        if carry_in is not None or params.get("warm"):
            cold = gainslib.init_carry(pts.shape[0],
                                       gainslib.planar_of(pts))
            if carry_in is None:
                carry = cold
            else:
                carry = gainslib.AdmmCarry(
                    **{k: np.asarray(v) for k, v in carry_in.items()})
                if any(tuple(getattr(carry, f).shape)
                       != tuple(getattr(cold, f).shape)
                       for f in ("x2", "s2", "x1", "s1")):
                    carry = cold   # shape/planarity flip: re-seed cold
            g, new_carry = gainslib.solve_gains(pts, adj, carry=carry)
            return {"gains": np.asarray(g), "n": n,
                    "carry": {k: np.asarray(v) for k, v in
                              new_carry._asdict().items()}}
        g = np.asarray(gainslib.solve_gains(pts, adj))
        return {"gains": g, "n": n}

    def _do_stats(self, params: dict):
        """Built-in ``stats`` kind: the swarmscope scrape surface as a
        request, so OFF-PROCESS clients fetch `prometheus_text()` /
        `snapshot()` over the existing wire protocol — the fleet is
        scrapeable without importing the package (a `WireClient`
        submit, or any future transport binding, is a scraper).
        ``format``: ``'prometheus'`` (default) returns ``{'text': ...}``;
        ``'snapshot'`` returns the full registry snapshot plus the
        service counter dict — both codec-serializable, so they cross
        the wire and the journal unchanged."""
        fmt = str(params.get("format", "prometheus"))
        if fmt == "prometheus":
            return {"format": fmt, "text": self.telemetry.prometheus_text()}
        if fmt == "snapshot":
            with self._lock:
                counters = {k: v for k, v in self.stats.items()}
            # pid + incarnation name the PROCESS generation serving
            # this scrape: `watch --follow` tells a respawned worker
            # process (both change) from a reconnect of the old one
            # (neither does)
            return {"format": fmt, "snapshot": self.telemetry.snapshot(),
                    "serve": counters, "pid": os.getpid(),
                    "incarnation": int(self.cfg.incarnation)}
        raise ValueError(f"unknown stats format {fmt!r} "
                         "(expected 'prometheus' or 'snapshot')")

    def _do_health(self, params: dict):
        """Built-in ``health`` kind: the live fleet-health surface as a
        request, scrapeable over the wire front end exactly like
        ``stats`` (docs/OBSERVABILITY.md §swarmwatch). Returns the SLO
        verdicts + burn rates from the swarmwatch engine (null when
        ``cfg.watch`` is off — liveness still reported), worker
        liveness, queue/in-flight levels refreshed AT SCRAPE TIME (not
        the last chunk boundary), and the service's promise counters —
        everything codec-serializable, so it crosses the wire and the
        journal unchanged."""
        t = self.telemetry
        self._watch_probe()            # a scrape reads NOW, not stale
        per_worker = {}
        for m in t.metrics():
            if m.name == "serve_worker_up" \
                    and m.labels.get("worker") is not None:
                per_worker[m.labels["worker"]] = bool(m.value)
        with self._lock:
            counts = dict(self.stats)
        out = {
            "t_wall": time.time(),
            "alive": bool(self.alive),
            # process identity (see _do_stats): respawn vs reconnect
            # are distinguishable from the scrape alone
            "pid": os.getpid(),
            "incarnation": int(self.cfg.incarnation),
            "watch_enabled": self.watch is not None,
            "watch": (self.watch.health()
                      if self.watch is not None else None),
            "workers": {
                "total": int(t.gauge("serve_workers_total").value),
                "up": int(t.gauge("serve_workers_up").value),
                "per_worker": per_worker,
            },
            "queue_depth": int(t.gauge("serve_queue_depth").value),
            "inflight": int(t.gauge("serve_inflight").value),
            "counts": counts,
        }
        return out

    # ------------------------------------------------------ finalization

    def _expired(self, job: _Job) -> bool:
        td = job.req.t_deadline
        return td is not None and time.time() > td

    def _timeout(self, job: _Job, late: bool = False) -> None:
        msg = (f"deadline ({job.req.deadline_s:.3f} s) exceeded at "
               f"chunk boundary {job.chunks_done}/{job.chunks_total}")
        if late:
            msg += " (work completed late; result discarded per contract)"
        self._journal_event("deadline", job, chunk=job.chunks_done,
                            late=late)
        self._finish(job, TIMED_OUT, error=ServeError(E_DEADLINE, msg))
        if self._ckpt_dir is not None:
            ckptlib.clear_checkpoints(self._ckpt_dir, self._stem(job))

    # ------------------------------------------- failover + cancellation

    def cancel(self, request_id: str,
               reason: str = "cancelled by client"):
        """Cancel one accepted request with a structured ``cancelled``
        error — the wire layer's disconnect semantics (a dead client's
        queue entries are cancelled, NEVER the running batch). Returns
        ``"queued"`` (the job was still queued: cancelled immediately),
        ``"resident"`` (mid-batch: marked, cancelled at its next chunk
        boundary — the same cancellation quantum deadlines use), or
        ``None`` (unknown or already terminal). Both non-None returns
        are truthy: callers that only care about "was there anything to
        cancel" can keep treating the result as a bool."""
        with self._lock:
            job = self._jobs.get(request_id)
            if job is None or job.finished:
                return None
            job.cancelled = reason
        if self._adm.cancel(job):      # was queued: cancel right now
            self._cancel_at_boundary(job)
            self._sample_queue()
            return "queued"
        return "resident"

    def _cancel_at_boundary(self, job: _Job) -> None:
        with self._lock:
            self.stats["cancelled"] += 1
        self._journal_event("cancelled", job,
                            reason=job.cancelled or "cancelled")
        self._finish(job, FAILED, error=ServeError(
            E_CANCELLED, job.cancelled or "cancelled"))
        if self._ckpt_dir is not None:
            ckptlib.clear_checkpoints(self._ckpt_dir, self._stem(job))

    def _failover_job(self, job: _Job, epoch: int, dead_uid: str,
                      solo: bool = False) -> None:
        """Fail one orphaned in-flight job over to the surviving
        workers (called by the pool supervisor with the dead worker's
        in-flight set). The dead incarnation joins the job's excluded
        set and the job is QUARANTINED (scheduled solo until a
        surviving chunk exonerates it). Only ``solo`` kills — the job
        was alone in the batch, with nobody else to blame — count
        toward the poison bound: at ``max_worker_exclusions`` of them
        the request terminates with a structured ``poisoned`` error
        instead of ping-ponging the fleet, while an innocent batch-mate
        of a co-incidental kill completes its quarantine round and
        walks free. Otherwise the job migrates THROUGH the checkpoint
        codec (its resident state is serialized here and restored
        template-validated on whichever surviving worker the placement
        hash names) and re-queues."""
        with self._lock:
            if job.finished or job.epoch != epoch:
                return                 # already terminal or re-owned
            if self._adm.contains(job):
                # a lease-lapsed (fenced, still-running) worker already
                # requeued this job at its chunk boundary before the
                # orphan snapshot was processed: the job is SAFE in the
                # queue — failing it over again would enqueue a second
                # copy (both picked into one batch, chunks run twice,
                # the bit-exact digest ruined). The boundary requeue
                # holds this same lock, so the check cannot race it.
                return
            job.epoch += 1
            job.worker = None
            job.excluded_workers.add(dead_uid)
            job.failovers += 1
            # quarantine: until a surviving chunk exonerates it, this
            # job is scheduled in a batch of ONE (admission pick) — the
            # next kill, if it comes, implicates exactly this request
            job.suspect = True
            if solo:
                job.solo_kills += 1
            exclusions = job.solo_kills
        if exclusions >= self.cfg.max_worker_exclusions:
            with self._lock:
                self.stats["poisoned"] += 1
            self.telemetry.counter("serve_poisoned_total").inc()
            self._journal_event("poisoned", job,
                                excluded=sorted(job.excluded_workers))
            self.log.error(
                "request %s POISONED: killed %d worker(s) while "
                "quarantined solo (%s) — terminating instead of "
                "wedging the fleet", job.req.request_id, exclusions,
                sorted(job.excluded_workers))
            self._finish(job, FAILED, error=ServeError(
                E_POISONED,
                f"request killed {exclusions} worker(s) while alone in "
                f"the batch ({sorted(job.excluded_workers)}) — excluded "
                "everywhere and terminated (max_worker_exclusions="
                f"{self.cfg.max_worker_exclusions})"))
            if self._ckpt_dir is not None:
                ckptlib.clear_checkpoints(self._ckpt_dir, self._stem(job))
            return
        # checkpoint-backed migration: serialize the orphaned resident
        # state through the codec so the next residency — on a DIFFERENT
        # worker — restores it template-validated and bit-identically
        # (the disk frame doubles as the crash-durability checkpoint).
        # Staged jobs (serve.staging): an in-flight job's staging row
        # may already hold the NEXT chunk's state (scatter-back lands
        # at dispatch, logical progress at finish), so migration never
        # reads the store — journaled services restore from the
        # per-chunk disk frame written at every resolve, unjournaled
        # ones from the consistent per-job shadow `_round_finish`
        # maintains (both proven bit-identical by the failover drills).
        if job.bucket[0] == "rollout":
            with self._lock:
                if job.state is None and job._shadow is not None:
                    # materialize the lazy shadow: state@chunks_done
                    # from the round output that resolved its last
                    # chunk (never the staging store — an in-flight
                    # job's store row may already hold the NEXT
                    # chunk's state). ``bi is None`` means the shadow
                    # is already a single materialized row (the
                    # eviction / stranded-readout form).
                    src, bi = job._shadow
                    job.state = (src if bi is None else
                                 stagelib.take_row(src, stagelib.i32(bi)))
            if job.state is not None:
                self._checkpoint(job, to_disk=self._ckpt_dir is not None)
            with self._lock:
                self._free_slot(job)
            job.state = None
            job._problem = None
            job._shadow = None
        with self._lock:
            if job.finished:
                return                 # raced a terminal path mid-ckpt
            job.status = QUEUED
            job.run_chunks = 0
            self.stats["requeued"] += 1
            # the migration record precedes pickability (same lock
            # hold): the surviving worker's `batched` must follow it in
            # the causal file order, so a postmortem reads
            # chunk -> migrated -> batched -> resumed, gap-free
            self._journal_event("migrated", job, dead_worker=dead_uid,
                                chunk=job.chunks_done,
                                failovers=job.failovers)
            self._adm.requeue(job)
        self.telemetry.counter("serve_requeued_total").inc()

    def _requeue_unowned(self, pairs: list) -> None:
        """Hand back jobs a ZOMBIE worker dequeued but never registered
        in-flight (the slot was replaced between its generation check
        and the pick): nobody owns them — not the queue, not any
        worker's in-flight set — so without this they would silently
        never run. Epoch-guarded and queue-checked like every requeue."""
        for job, epoch in pairs:
            with self._lock:
                if job.finished or job.epoch != epoch \
                        or self._adm.contains(job):
                    continue
                job.status = QUEUED
                job.worker = None
                # the handback is a real state transition: journal it
                # in the same lock hold (like _failover_job's
                # `migrated`) so the postmortem reads an unbroken
                # ... batched -> queued -> batched ... chain instead
                # of a gap where the job silently changed hands
                self._journal_event("queued", job, reason="unowned")
                self._adm.requeue(job)

    def _journal_event_owned(self, event: str, job: _Job, epoch: int,
                             **fields) -> None:
        """Emit a request event ONLY while this residency still owns
        the job (finished/epoch checked under the lock): a fenced
        zombie worker must never append a `batched` record after the
        job's `migrated`/`resolved` — causal file order is the
        postmortem's ground truth."""
        with self._lock:
            if job.finished or job.epoch != epoch:
                return
            self._journal_event(event, job, **fields)

    def _journal_event(self, event: str, job: Optional[_Job] = None,
                       **fields) -> None:
        """Append one schema'd lifecycle record to the journal's
        torn-tail-tolerant events.log (`telemetry.lifecycle`): the
        swarmtrace stream `telemetry.postmortem` reconstructs timelines
        from. ``job=None`` emits a fleet-scope event (worker death).
        With ``cfg.trace`` off only the failover/migrated/poisoned
        ledger (the PR-8 recovery counters) is journaled."""
        if self._trace is None:
            return
        if not self.cfg.trace and event not in _LEDGER_EVENTS:
            return
        if not self._fence_ok():
            self.telemetry.counter("serve_fenced_writes_total").inc()
            return
        self._trace.emit(
            event,
            request_id=job.req.request_id if job is not None else None,
            trace_id=job.req.trace_id if job is not None else "",
            incarnation=self.cfg.incarnation,
            **fields)

    def _flush_spans(self, reason: str) -> None:
        """Dump the span ring to the journal NOW (the worker-death
        path: a SIGKILLed or wedged worker cannot flush itself, so the
        supervisor flushes on its behalf when it declares it dead)."""
        if self._span_dump is not None:
            self._span_dump.dump(reason)

    def _finish(self, job: _Job, status: str, value=None,
                error: Optional[ServeError] = None,
                journal: bool = True) -> None:
        with self._lock:
            # atomic once-guard: the close() sweep, the round-level
            # exception handler, and a racing submit() may all try to
            # terminate the same job — first caller wins, stats count once
            if job.finished:
                return
            job.finished = True
        t_done = time.time()
        queued_s = (((job.t_first_run or time.monotonic()) - job.t_accept)
                    if job.t_accept else 0.0)
        res = Result(
            request_id=job.req.request_id, status=status, value=value,
            error=error,
            latency_s=max(0.0, t_done - job.req.t_submit),
            queued_s=max(0.0, queued_s), chunks=job.chunks_done,
            preemptions=job.preemptions, resumed=job.resumed,
            failovers=job.failovers, trace_id=job.req.trace_id)
        # durable-then-visible: the done-frame is written before the
        # client can observe the result, so "resolved but not journaled"
        # is impossible and recovery never re-runs finished work
        if journal and self._journal is not None:
            if not self._fence_ok():
                # zombie write no-op: the successor incarnation owns
                # this journal — ITS recovery already re-admitted the
                # request, and a done-frame from us would overwrite the
                # live incarnation's ledger
                self.telemetry.counter("serve_fenced_writes_total").inc()
            else:
                _write_frame(
                    self._done_path(job.req.request_id),
                    {"value": value,
                     "error": error.to_row() if error else None},
                    ckptlib.make_manifest(
                        "serve_done", "-", chunk=job.chunks_done,
                        request_id=job.req.request_id, status=status,
                        latency_s=res.latency_s, queued_s=res.queued_s,
                        preemptions=job.preemptions, resumed=job.resumed,
                        failovers=job.failovers,
                        tenant=job.req.tenant, req_kind=job.req.kind,
                        incarnation=self.cfg.incarnation,
                        t_done=t_done, trace_id=job.req.trace_id))
        # the terminal trace record: journaled whether or not the
        # done-frame was (a close()-raced submit resolves its ticket
        # with journal=False, but the timeline still owes its ending)
        self._journal_event(
            "resolved", job, status=status, chunks=job.chunks_done,
            latency_s=res.latency_s, preemptions=job.preemptions,
            failovers=job.failovers,
            error_code=error.code if error else None)
        job.status = status
        self.telemetry.counter("serve_" + {
            COMPLETED: "completed", TIMED_OUT: "deadline_miss",
            FAILED: "failed"}[status] + "_total").inc()
        self.telemetry.histogram(
            "serve_latency_s",
            labels={"tenant": job.req.tenant}).observe(res.latency_s)
        with self._lock:
            key = {COMPLETED: "completed", TIMED_OUT: "timed_out",
                   FAILED: "failed"}[status]
            self.stats[key] += 1
            # retire the request record: an always-on service must not
            # retain per-request device state (SimState pytree, problem
            # arrays, checkpoint bytes, staging rows) or unbounded job
            # maps forever. The client's ticket keeps the Result alive;
            # the service keeps only a bounded terminal cache for
            # idempotent duplicate submits (journal done-frames persist
            # on disk).
            self._free_slot(job)
            job.state = None
            job._problem = None
            job._ckpt_bytes = None
            job._shadow = None
            self._jobs.pop(job.req.request_id, None)
            self._done_prior[job.req.request_id] = res
            while len(self._done_prior) > max(0, self.cfg.done_retention):
                self._done_prior.pop(next(iter(self._done_prior)))
        job.ticket._resolve(res)

    # ---------------------------------------------------------- recovery

    def _recover(self) -> None:
        """Rebuild the promise ledger from the journal: every accepted
        request without a done-frame is re-admitted (resuming from its
        rollout checkpoint when one survived) — the zero-silent-loss
        half the SIGKILL proof exercises. Already-terminal requests are
        cached so duplicate submits resolve instantly."""
        assert self._journal is not None
        if not self._journal.is_dir():
            return
        events = self._journal / "events.log"
        if events.is_file():
            # the worker-lifecycle ledger is APPEND-only: a crash
            # mid-append leaves a torn trailing record, which the
            # frame-log reader treats as clean EOF (any NON-trailing
            # corruption still raises CheckpointCorrupt loudly)
            frames, torn = ckptlib.read_frame_log(events)
            for _, man in frames:
                # `migrated` is the swarmtrace name for the per-job
                # failover record; `requeue` its pre-trace spelling —
                # one reader serves both generations of journal
                key = {"failover": "failovers", "requeue": "requeued",
                       "migrated": "requeued",
                       "poisoned": "poisoned"}.get(man.get("event"))
                if key is not None:
                    # construction-time replay: _recover() runs from
                    # __init__ before any worker thread exists
                    self.stats[key] += 1   # jaxcheck: disable=JC101
            if torn:
                self.log.warning(
                    "events.log ends in a torn record (crash "
                    "mid-append) — dropped it as clean EOF; %d prior "
                    "lifecycle record(s) recovered", len(frames))
        for done in sorted(self._journal.glob("req_*.done")):
            payload, man = _read_frame(done)
            err = payload.get("error")
            prior = Result(
                request_id=man["request_id"], status=man["status"],
                value=payload.get("value"),
                error=ServeError(**err) if err else None,
                latency_s=float(man.get("latency_s", 0.0)),
                queued_s=float(man.get("queued_s", 0.0)),
                preemptions=int(man.get("preemptions", 0)),
                resumed=bool(man.get("resumed", False)),
                failovers=int(man.get("failovers", 0)),
                trace_id=str(man.get("trace_id", "")))
            with self._lock:
                self._done_prior[man["request_id"]] = prior
        for reqf in sorted(self._journal.glob("req_*.req")):
            payload, man = _read_frame(reqf)
            rid = man["request_id"]
            with self._lock:
                already_done = rid in self._done_prior
            if already_done:
                continue
            # the acceptance frame carries the ORIGINAL trace_id: a
            # request's causal identity survives the process that
            # accepted it (the whole point of minting at submit)
            req = Request(kind=man["req_kind"], params=payload["params"],
                          tenant=man["tenant"], request_id=rid,
                          deadline_s=man.get("deadline_s"),
                          t_submit=float(man["t_submit"]),
                          trace_id=str(man.get("trace_id", "")))
            try:
                job = self._make_job(req)
            except ValueError as e:     # journaled garbage: loud error
                job = _Job(req=req, ticket=Ticket(rid), bucket=("?",))
                with self._lock:
                    self._jobs[rid] = job
                self._finish(job, FAILED,
                             error=ServeError(E_EXECUTION,
                                              f"unrecoverable params: {e}"))
                continue
            if self._ckpt_dir is not None and ckptlib.latest_checkpoint(
                    self._ckpt_dir, f"req_{rid}") is not None:
                job.resumed = True
                with self._lock:
                    self.stats["resumed"] += 1
                self.telemetry.counter("serve_resumed_total").inc()
            with self._lock:
                self._jobs[rid] = job
            # the recovery re-queue is itself a trace event: the
            # postmortem reads the crash gap as queued(recovery) ->
            # batched on whichever incarnation picks the job up
            self._journal_event("queued", job, reason="recovery")
            self._adm.admit(job, force=True)
            with self._lock:
                self.stats["accepted"] += 1
            self.telemetry.counter("serve_accepted_total").inc()
        with self._lock:
            n_jobs, n_prior = len(self._jobs), len(self._done_prior)
        if n_jobs:
            self.log.warning(
                "serve recovery: re-admitted %d unfinished request(s) "
                "from %s (%d already terminal)", n_jobs,
                self._journal, n_prior)

    # --------------------------------------------------------- telemetry

    def _sample_boundary(self, live: int, worker=None) -> None:
        """Chunk-boundary scheduler gauges (docs/OBSERVABILITY.md): the
        batch-bucket occupancy (live device-batch slots / max_batch —
        the continuous-batching fill factor `serve_throughput` plots)
        and the admission queue depth, recorded both as last-value
        gauges and as distributions over the run. With a worker handed
        in, the same occupancy sample also lands in that worker's
        labeled per-worker distribution (the failover drills read it to
        show surviving workers absorbing the dead one's share)."""
        t = self.telemetry
        occ = live / max(1, self.cfg.max_batch)
        depth = self._adm.pending()
        # gauges and their distributions carry DISTINCT names (_hist):
        # snapshot() keys by name+labels and Prometheus forbids two
        # families sharing one name, so a collision would corrupt both
        # export surfaces
        t.gauge("serve_bucket_occupancy").set(occ)
        t.histogram("serve_bucket_occupancy_hist").observe(occ)
        t.gauge("serve_queue_depth").set(depth)
        t.histogram("serve_queue_depth_hist").observe(depth)
        if worker is not None:
            lbl = {"worker": str(worker.slot)}
            t.histogram("serve_worker_occupancy_hist",
                        labels=lbl).observe(occ)
            t.counter("serve_worker_chunks_total", labels=lbl).inc(live)
        t.gauge("serve_inflight").set(self._pool.inflight_total())

    def _sample_queue(self) -> None:
        """Refresh the queue-depth GAUGE outside chunk boundaries
        (submit / reject / cancel / the watch probe): an idle or wedged
        service must not show a stale depth forever — the gauge is the
        liveness signal swarmwatch's queue-saturation and silent-loss
        SLOs read, and chunk boundaries never come on an idle service.
        Only the gauge: the ``*_hist`` distributions stay
        boundary-sampled so the per-round statistics the committed
        throughput artifact reports keep their sampling cadence."""
        self.telemetry.gauge("serve_queue_depth").set(self._adm.pending())

    def _watch_probe(self) -> None:
        """Sampler pre-tick hook: refresh the liveness gauges so every
        sample reads CURRENT state, not the last chunk boundary's."""
        self._sample_queue()
        self.telemetry.gauge("serve_inflight").set(
            self._pool.inflight_total())

    def _emit_alert(self, ev: dict) -> None:
        """Append one swarmwatch alert transition to the journal's
        events.log as a schema'd fleet-scope ``alert`` record (the
        postmortem and the live surface share one stream). Unjournaled
        services keep the in-memory engine state only."""
        self._journal_event("alert", None, **ev)

    def _attribute_device(self, jobs: list, span_s: float) -> None:
        """Per-tenant device-time cost accounting: one round's device
        span divided across the OCCUPIED batch rows into
        ``serve_device_s{tenant,kind}`` counters — padding rows bill
        nobody, so the counters sum to wall actually spent serving.
        Makes per-tenant SLOs evaluable over the sampled series and
        turns the round-robin fairness claim into a measured cost
        series (the matching-under-drift framing needs per-tenant cost,
        not spot checks)."""
        if not jobs or span_s <= 0:
            return
        share = span_s / len(jobs)
        for job in jobs:
            self.telemetry.counter(
                "serve_device_s",
                labels={"tenant": job.req.tenant,
                        "kind": job.req.kind}).inc(share)

    def serve_stats(self) -> ServeStats:
        """Plain-data swarmscope snapshot of this service's registry
        (`serve.stats.ServeStats`; docs/OBSERVABILITY.md)."""
        return ServeStats.of(self)

    def row_fields(self) -> dict:
        """Executor + service counters for results-JSON rows (the same
        shape the suites commit; `benchmarks/check_results.py`)."""
        out = dict(self._execu.row_fields())
        out["serve"] = {k: v for k, v in self.stats.items()}
        return out

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
