"""ServeStats — the swarmserve telemetry surface (docs/SERVICE.md,
docs/OBSERVABILITY.md).

Every `SwarmService` owns a private `MetricsRegistry` (services must
not cross-pollute — the soak runs a crashed service and a reference
service in one process) and records into it:

- **admission counters**: ``serve_accepted_total``,
  ``serve_rejected_total`` + the ``serve_retry_after_s`` histogram of
  backpressure hints handed out;
- **lifecycle counters**: completed / failed / preempted / resumed /
  ``serve_deadline_miss_total`` (the timed-out ledger);
- **scheduler gauges, sampled at every chunk boundary** (the service's
  only scheduling points): ``serve_queue_depth`` and
  ``serve_bucket_occupancy`` (live jobs / max_batch slots — the
  continuous-batching fill factor the `serve_throughput` artifact
  plots), plus the ``*_hist``-suffixed distributions so a run reports
  percentiles, not last values (distinct names: two export families
  must never share one);
- **per-tenant end-to-end latency** histograms
  (``serve_latency_s{tenant=...}``): accept -> terminal wall seconds,
  observed in `_finish` for every terminal status;
- **per-tenant device-time cost** counters
  (``serve_device_s{tenant,kind}``): each round's device span divided
  across its occupied batch rows (swarmwatch cost accounting,
  docs/OBSERVABILITY.md §swarmwatch);
- **round spans** in the registry's flight recorder (name
  ``serve.round``, attrs: round index, bucket, batch size).

`ServeStats.of(service)` reduces that registry to one plain-data
record; `.compact()` is the three-number summary `bench.py` embeds in
its structured row.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

__all__ = ["ServeStats"]


@dataclasses.dataclass
class ServeStats:
    """Plain-data snapshot of one service's telemetry registry."""

    counts: dict                 # accepted/rejected/completed/... ints
    queue_depth: int             # last sampled depth (chunk boundary)
    occupancy: float             # last sampled live/max_batch fill
    occupancy_mean: float        # mean over all sampled rounds
    occupancy_p95: float
    queue_depth_mean: float
    queue_depth_p95: float
    latency_s: dict              # tenant -> {count, p50, p95, p99}
    rounds: int                  # scheduler rounds executed
    chunks: int                  # device chunks executed
    spans_recorded: int
    workers: int                 # configured worker slots (fleet size)
    workers_up: int              # slots currently up (worker_up gauges)
    per_worker: dict             # slot -> {up, chunks, occupancy_mean}
    # swarmtrace stream census (journaled services; zeros otherwise):
    # events appended to events.log, appends the filesystem refused
    # (loudly logged), and wall seconds spent appending — the numerator
    # of the trace_soak overhead measurement
    trace_events: int = 0
    trace_lost: int = 0
    trace_spent_s: float = 0.0
    # swarmwatch per-tenant device-time cost accounting
    # (docs/OBSERVABILITY.md §swarmwatch): tenant -> {kind: seconds},
    # each round's device span attributed across its occupied batch
    # rows (serve_device_s{tenant,kind} counters)
    device_s: dict = dataclasses.field(default_factory=dict)

    @classmethod
    def of(cls, service) -> "ServeStats":
        reg = service.telemetry
        counts = {}
        for key in ("accepted", "rejected", "completed", "failed",
                    "preempted", "resumed", "deadline_miss",
                    "failover", "requeued", "poisoned"):
            counts[key] = int(reg.counter(f"serve_{key}_total").value)
        occ = reg.histogram("serve_bucket_occupancy_hist")
        dep = reg.histogram("serve_queue_depth_hist")
        occ_row, dep_row = occ.to_row(), dep.to_row()
        lat = {}
        per_worker: dict = {}
        device_s: dict = {}
        for m in reg.metrics():
            if m.name == "serve_device_s" and m.labels.get("tenant"):
                device_s.setdefault(m.labels["tenant"], {})[
                    m.labels.get("kind", "?")] = round(float(m.value), 6)
            elif m.name == "serve_latency_s" and m.labels.get("tenant"):
                row = m.to_row()
                lat[m.labels["tenant"]] = {
                    "count": row["count"],
                    "p50": row.get("p50"), "p95": row.get("p95"),
                    "p99": row.get("p99")}
            elif m.labels.get("worker") is not None:
                w = per_worker.setdefault(
                    m.labels["worker"],
                    {"up": False, "chunks": 0, "occupancy_mean": 0.0})
                if m.name == "serve_worker_up":
                    w["up"] = bool(m.value)
                elif m.name == "serve_worker_chunks_total":
                    w["chunks"] = int(m.value)
                elif m.name == "serve_worker_occupancy_hist":
                    w["occupancy_mean"] = round(float(
                        m.to_row().get("mean", 0.0)), 3)
        with service._lock:
            rounds = int(service.stats.get("rounds", 0))
            chunks = int(service.stats.get("chunks", 0))
        return cls(
            counts=counts,
            queue_depth=int(reg.gauge("serve_queue_depth").value),
            occupancy=float(reg.gauge("serve_bucket_occupancy").value),
            occupancy_mean=float(occ_row.get("mean", 0.0)),
            occupancy_p95=float(occ_row.get("p95", 0.0)),
            queue_depth_mean=float(dep_row.get("mean", 0.0)),
            queue_depth_p95=float(dep_row.get("p95", 0.0)),
            latency_s=lat, rounds=rounds, chunks=chunks,
            spans_recorded=int(reg.recorder.recorded),
            workers=int(reg.gauge("serve_workers_total").value),
            workers_up=sum(1 for w in per_worker.values() if w["up"]),
            per_worker=per_worker,
            trace_events=(service._trace.emitted
                          if service._trace is not None else 0),
            trace_lost=(service._trace.lost
                        if service._trace is not None else 0),
            trace_spent_s=(round(service._trace.spent_s, 6)
                           if service._trace is not None else 0.0),
            device_s=device_s)

    def compact(self) -> dict:
        """The bench-row summary: bucket occupancy, queue depth,
        preemption count, the admission ledger, and the fleet
        provenance (worker count + failover events — a row served by a
        degraded fleet says so) — small enough to ride every structured
        one-line row, degraded ones included."""
        return {
            "occupancy_mean": round(self.occupancy_mean, 3),
            "queue_depth": self.queue_depth,
            "preempted": self.counts.get("preempted", 0),
            "accepted": self.counts.get("accepted", 0),
            "rejected": self.counts.get("rejected", 0),
            "deadline_miss": self.counts.get("deadline_miss", 0),
            "workers": self.workers,
            "failovers": self.counts.get("failover", 0),
        }

    @staticmethod
    def empty_compact() -> dict:
        """The same key set, zeroed — degraded rows where no service
        ever started (probe failure, watchdog) still carry the
        telemetry block so row consumers need no key-presence logic."""
        return {"occupancy_mean": 0.0, "queue_depth": 0, "preempted": 0,
                "accepted": 0, "rejected": 0, "deadline_miss": 0,
                "workers": 0, "failovers": 0}

    def to_row(self) -> dict:
        return dataclasses.asdict(self)
