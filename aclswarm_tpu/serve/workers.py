"""Supervised multi-worker execution for swarmserve (docs/SERVICE.md).

PR 6 deliberately shipped ONE worker thread = one device stream = one
single point of failure — the exact design the paper's fleet forbids
(every vehicle runs the pipeline onboard; the swarm survives member
loss). This module removes it: a `WorkerPool` runs N supervised device
workers (one per mesh slice on a multi-device host via
`parallel.mesh.slice_devices`, N host threads sharing the device on the
CPU fallback host) and treats worker death as a ROUTINE event:

- **placement = matching under drift**: the admission layer shards
  shape buckets across workers with rendezvous hashing — each bucket
  deterministically owns one alive worker (so a compiled shape lives on
  exactly one worker, never recompiled N times), and when the alive set
  churns only the buckets placed on the dead worker re-match (the
  minimal-disruption property; the same streaming-assignment-under-
  drift shape as PAPERS.md's consensus-based distributed resource
  matching, arXiv:1904.04318);
- **heartbeat + lease**: every worker stamps a heartbeat each loop
  iteration; the supervisor declares a worker dead when its thread
  exits OR its lease lapses (a wedged-but-alive thread), fences it so a
  zombie can never touch migrated jobs (per-job epoch counters make
  stale writes no-ops), and requeues its in-flight work;
- **checkpoint-backed migration**: an orphaned rollout is serialized
  through the resilience codec (disk when journaled, in-memory frame
  otherwise) and restored template-validated on a DIFFERENT worker —
  resume is bit-identical, proven by `serve.smoke --multiworker` and
  `benchmarks/serve_multiworker_soak.py`;
- **poison bound**: each migration records the dead worker incarnation
  in the job's excluded set; after ``max_worker_exclusions`` distinct
  kills the request terminates with a structured ``poisoned`` error
  instead of ping-ponging the fleet to death;
- **circuit breaker + backoff-gated rejoin**: a dead worker slot
  respawns after a `utils.retry.RetryPolicy` backoff that grows with
  consecutive deaths; past ``max_worker_restarts`` the slot retires
  (circuit open). While capacity is degraded the admission retry-after
  hint scales by total/alive (`AdmissionControl.set_capacity`).

Host-side only: the pool schedules the same jitted entry points the
single worker drove; the compiled surface (HLO baseline) is unchanged.
"""
from __future__ import annotations

import dataclasses
import threading
import time
import zlib
from typing import TYPE_CHECKING, List, Optional, Tuple

from aclswarm_tpu.resilience import InjectedCrash
from aclswarm_tpu.utils.locks import OrderedLock
from aclswarm_tpu.utils.retry import RetryPolicy, delay_for

if TYPE_CHECKING:                                   # pragma: no cover
    from aclswarm_tpu.serve.service import SwarmService

# worker-targeted crash sites: `serve.w{slot}` consulted with the
# SLOT's cumulative round count (stable across respawns, so one drill
# can script repeated kills of the same slot); the process-level
# `serve` site keeps its PR-6 global-round semantics in service.py
WORKER_SITE = "serve.w{slot}"

# worker lifecycle states
UP = "up"
COOLDOWN = "cooldown"      # dead; rejoin gated by the backoff policy
RETIRED = "retired"        # circuit open: max_worker_restarts exceeded
EXITED = "exited"          # clean exit (stop/drain) — NOT a death


@dataclasses.dataclass
class Worker:
    """One supervised worker slot. ``uid`` names the INCARNATION
    (slot.generation): exclusion sets hold uids, so a respawned slot is
    a fresh candidate while placement stays keyed on the stable slot."""

    slot: int
    gen: int = 0
    thread: Optional[threading.Thread] = None
    state: str = COOLDOWN
    last_beat: float = 0.0
    round: int = 0              # cumulative across incarnations
    fails: int = 0              # CONSECUTIVE deaths (backoff input);
    #                             reset by a completed round, so an
    #                             always-on fleet absorbing occasional
    #                             isolated deaths never retires a slot
    rejoin_at: float = 0.0
    fenced: bool = False        # lease-lapsed zombie: must not touch jobs
    device: object = None       # this slot's mesh-slice lead device
    inflight: List[Tuple[object, int]] = dataclasses.field(
        default_factory=list)   # [(job, epoch-at-pick)] — with the
    #                             pipeline, BOTH the dispatched-pending
    #                             round's pairs and the newly picked ones
    staging: dict = dataclasses.field(default_factory=dict)
    #                           # bucket -> serve.staging.BucketStaging:
    #                             this INCARNATION's resident batches
    #                             (reset on respawn; a dead worker's
    #                             stranded rows are read out by the new
    #                             owner under the service lock)

    @property
    def uid(self) -> str:
        return f"{self.slot}.{self.gen}"


def place_slot(bucket, candidates: List[int],
               key: Optional[bytes] = None) -> Optional[int]:
    """Rendezvous (highest-random-weight) hash of a shape bucket onto
    the candidate worker slots: every caller agrees on the owner
    without coordination, and removing one slot re-matches ONLY the
    buckets it owned — the minimal re-matching under churn that makes
    worker death cheap. Deterministic (crc32, no `random`).
    ``key`` is the precomputed ``repr(bucket).encode()`` — the hot
    eligibility path caches it per job (buckets are immutable) so
    queue scans don't re-encode on every poll.

    Candidates may be ints (thread slots) or strings (the process
    fleet's ``slot.gen`` uids — hashing over the INCARNATION set is
    what makes a respawn re-place only the dead incarnation's
    buckets); ties break to the smallest candidate either way."""
    if not candidates:
        return None
    if key is None:
        key = repr(bucket).encode()
    return min(candidates,
               key=lambda s: (-zlib.crc32(key + f":{s}".encode()), s))


class WorkerPool:
    """N supervised worker threads + one supervisor thread.

    The pool owns worker LIFECYCLE (spawn, heartbeat, lease, declare-
    dead, failover, backoff-gated rejoin); the service keeps ownership
    of request state (rounds, finish, journal). The split keeps lock
    ordering simple: admission's queue lock may nest the pool lock
    (``on_take``), the pool lock never nests admission's."""

    def __init__(self, service: "SwarmService", cfg):
        self.svc = service
        self.cfg = cfg
        self.log = service.log
        self._lock = OrderedLock("serve.pool",
                                 registry=service.telemetry)
        self._slots = [Worker(slot=i) for i in range(max(1, cfg.workers))]
        self._supervisor: Optional[threading.Thread] = None
        self._started = False
        self._rejoin_policy = RetryPolicy(
            attempts=max(1, cfg.max_worker_restarts + 1),
            base_s=cfg.rejoin_base_s, max_s=cfg.rejoin_max_s)
        # immutable snapshot of alive workers, rebuilt under the pool
        # lock and read LOCK-FREE by eligibility predicates (which run
        # under admission's queue lock — taking the pool lock there
        # would invert the lock order)
        self._alive_view: Tuple[Worker, ...] = ()
        service.telemetry.gauge("serve_workers_total").set(
            len(self._slots))

    # ----------------------------------------------------------- lifecycle

    def start(self) -> None:
        """Spawn every worker slot + the supervisor (idempotent)."""
        with self._lock:
            if self._started:
                return
            self._started = True
        devices = self._slice_devices()
        for w, dev in zip(self._slots, devices):
            w.device = dev
            self._spawn(w)
        self._supervisor = threading.Thread(
            target=self._supervise, daemon=True, name="swarmserve-sup")
        self._supervisor.start()

    def _slice_devices(self) -> list:
        """One mesh slice per worker (`parallel.mesh.slice_devices`);
        None per slot when the host has a single device (the CPU
        fallback: N threads share the default stream)."""
        try:
            from aclswarm_tpu.parallel.mesh import slice_devices
            slices = slice_devices(len(self._slots))
        except Exception as e:          # noqa: BLE001 — degrade loudly
            self.log.warning("worker device slicing unavailable (%s); "
                             "workers share the default device", e)
            return [None] * len(self._slots)
        distinct = {d.id for sl in slices for d in sl}
        if len(distinct) <= 1:
            # single-device host: no point pinning — the workers share
            # the default stream and the placement stays implicit
            return [None] * len(self._slots)
        return [sl[0] if sl else None for sl in slices]

    def _spawn(self, w: Worker) -> None:
        with self._lock:
            w.gen += 1
            w.state = UP
            w.fenced = False
            w.last_beat = time.monotonic()
            w.inflight = []
            w.staging = {}
            t = threading.Thread(target=self._run_worker, args=(w,),
                                 daemon=True,
                                 name=f"swarmserve-w{w.slot}.{w.gen}")
            w.thread = t
            self._rebuild_alive_view()
        self.svc.telemetry.gauge(
            "serve_worker_up", labels={"worker": str(w.slot)}).set(1)
        self._publish_capacity()
        t.start()

    def _rebuild_alive_view(self) -> None:
        self._alive_view = tuple(w for w in self._slots if w.state == UP)

    def _publish_capacity(self) -> None:
        alive = sum(1 for w in self._slots if w.state == UP)
        self.svc._adm.set_capacity(alive, len(self._slots))
        self.svc.telemetry.gauge("serve_workers_up").set(alive)

    @property
    def started(self) -> bool:
        return self._started

    def any_alive(self) -> bool:
        """True while anything can still make progress: a live worker
        thread, or the supervisor (which can respawn one)."""
        if any(w.thread is not None and w.thread.is_alive()
               for w in self._slots):
            return True
        return (self._supervisor is not None
                and self._supervisor.is_alive())

    def inflight_total(self) -> int:
        with self._lock:
            return sum(len(w.inflight) for w in self._slots)

    def join(self, timeout: float) -> None:
        deadline = time.monotonic() + timeout
        threads = [w.thread for w in self._slots if w.thread is not None]
        threads += [self._supervisor] if self._supervisor else []
        for t in threads:
            t.join(max(0.0, deadline - time.monotonic()))

    # --------------------------------------------------------- scheduling

    def eligible(self, job, w: Worker) -> bool:
        """Is ``job`` placed on worker ``w``? Runs under admission's
        queue lock — reads the published alive view only, never the
        pool lock. A job's excluded incarnations (workers it already
        died on) are skipped; the rendezvous hash over the remaining
        alive slots names exactly one owner."""
        view = self._alive_view
        if w.fenced or w.state != UP:
            return False
        cands = [x.slot for x in view
                 if x.uid not in job.excluded_workers]
        key = job.__dict__.get("_place_key")
        if key is None:
            key = job.__dict__["_place_key"] = repr(job.bucket).encode()
        return place_slot(job.bucket, cands, key=key) == w.slot

    # -------------------------------------------------------- worker loop

    def _mark_exited(self, w: Worker, my_gen: int) -> None:
        """Record a CLEAN exit (stop/drain): the supervisor must not
        mistake it for a death and fail over nothing."""
        with self._lock:
            if w.gen == my_gen and w.state == UP:
                w.state = EXITED
                self._rebuild_alive_view()
        self._publish_capacity()

    def _drop_inflight(self, w: Worker, my_gen: int, pairs: list) -> None:
        """Unregister one round's pairs (identity-matched: `_Job` is a
        dataclass whose field-wise __eq__ must never run on pytrees)."""
        with self._lock:
            if w.gen == my_gen:
                done = {id(j) for j, _ in pairs}
                w.inflight = [p for p in w.inflight
                              if id(p[0]) not in done]

    def _run_worker(self, w: Worker) -> None:
        """The double-buffered worker loop (docs/SERVICE.md
        §scheduling): each iteration PICKS and STARTS round k+1 (pack +
        async dispatch — the device begins immediately), THEN FINISHES
        round k (the one blocking device_get + resolve). The host's
        pack/unpack/resolve work for one round overlaps the device's
        compute for the next; a round whose bucket or config cannot
        pipeline (single-shot kinds, ``staging=False``) completes
        inside `_round_start` and leaves no pending half."""
        from aclswarm_tpu.serve.service import _Fenced

        svc = self.svc
        my_gen = w.gen
        pending = None              # the dispatched-unresolved round

        def _abandon(pend):
            """A round dropped between start and finish (scripted
            kill, fence, zombie exit) still owes its parent
            `serve.round` span — its child pack/stack/dispatch spans
            already recorded, and a missing parent would make child
            sums exceed the round sum (read as mis-nesting by the
            breakdown validator) for a cause that is span loss."""
            if pend is not None:
                svc._emit_round_span(pend.start_dur, pend.span_attrs,
                                     error=True)

        while not svc._stop.is_set():
            w.last_beat = time.monotonic()
            if w.fenced or w.gen != my_gen:
                _abandon(pending)
                return              # zombie: the supervisor replaced us
                #                     (pending work was failed over at
                #                     declare-dead with the in-flight
                #                     set — nothing to hand back)

            taken: dict = {}

            def _take(jobs, w=w, my_gen=my_gen, taken=taken):
                # runs under admission's queue lock: the dequeue, the
                # epoch capture, and the in-flight registration are ONE
                # atomic step. The picked batch is returned through
                # `taken`, never re-read from the shared slot record —
                # a replacement incarnation's in-flight list must be
                # invisible to this thread. APPEND, don't replace: the
                # pending round's pairs are still in flight.
                with self._lock:
                    pairs = [(j, j.epoch) for j in jobs]
                    taken["pairs"] = pairs
                    if w.gen == my_gen and not w.fenced:
                        w.inflight = w.inflight + pairs
                        for j in jobs:
                            j.worker = w.slot
                            j.pick_batch = len(jobs)
                    else:
                        taken["stale"] = True

            # with a round pending, poll instead of parking: the next
            # pick either overlaps the device or we go finish the round
            jobs = svc._adm.pick(self.cfg.max_batch,
                                 timeout=(0.0 if pending is not None
                                          else self.cfg.idle_poll_s),
                                 eligible=lambda j: self.eligible(j, w),
                                 on_take=_take)
            if not jobs and pending is None:
                if (svc._draining.is_set() and svc._adm.empty()
                        and self.inflight_total() == 0):
                    self._mark_exited(w, my_gen)
                    return          # all tenants idle: clean exit
                continue
            pairs = taken.get("pairs", [])
            if taken.get("stale"):
                # the slot was replaced between the loop-top gen check
                # and the pick: this thread is a zombie, but it just
                # dequeued real jobs that are registered NOWHERE — hand
                # them straight back so the live fleet runs them
                svc._requeue_unowned(pairs)
                _abandon(pending)
                return
            def _finish_now(pend, busy, w=w, my_gen=my_gen):
                """Resolve one pending round; True = this thread must
                die (scripted kill / fenced)."""
                try:
                    svc._round_finish(pend, w, busy=busy)
                except InjectedCrash as e:
                    self.log.warning(
                        "serve worker %s dying as scripted: %s",
                        w.uid, e)
                    svc._emit_round_span(pend.start_dur,
                                         pend.span_attrs, error=True)
                    return True
                except _Fenced:
                    svc._emit_round_span(pend.start_dur,
                                         pend.span_attrs, error=True)
                    return True
                except Exception as e:      # noqa: BLE001 — recorded
                    svc._fail_round(pend.pairs, e)
                self._drop_inflight(w, my_gen, pend.pairs)
                return False

            # quarantine isolation: a SUSPECT's solo round must never
            # overlap another round — a kill during its residency has
            # to implicate exactly that batch (the poison bound's
            # blame unit). With overlap allowed, every death would
            # leave two rounds' orphans and a max_batch=1 fleet under
            # load could never attribute a solo kill unambiguously —
            # the poison request would ping-pong workers into the
            # circuit breaker instead of terminating `poisoned`.
            if jobs and pending is not None and (
                    any(getattr(j, "suspect", False) for j in jobs)
                    or any(getattr(j, "suspect", False)
                           for j in pending.jobs)):
                if _finish_now(pending, 0):
                    return
                pending = None
            new_pending = None
            if jobs:
                w.round += 1
                try:
                    new_pending = svc._round_start(
                        pairs, w,
                        busy_ids=(frozenset(id(j) for j in pending.jobs)
                                  if pending is not None
                                  else frozenset()))
                except InjectedCrash as e:
                    # the scripted worker kill: die ABRUPTLY, in-flight
                    # work still registered — exactly what a SIGKILLed
                    # worker process leaves behind. The supervisor
                    # detects the dead thread and fails the work (BOTH
                    # rounds' — pending included) over to a survivor.
                    self.log.warning(
                        "serve worker %s dying as scripted: %s", w.uid, e)
                    _abandon(pending)
                    return
                except _Fenced:
                    _abandon(pending)
                    return          # fenced mid-round: jobs failed over
                except Exception as e:      # noqa: BLE001 — recorded
                    svc._fail_round(pairs, e)
                    self._drop_inflight(w, my_gen, pairs)
                if new_pending is None:
                    # round completed inside _round_start (single-shot,
                    # legacy path, pipeline off, or fully gated out)
                    self._drop_inflight(w, my_gen, pairs)
            if pending is not None:
                if _finish_now(pending,
                               len(new_pending.jobs) if new_pending
                               else 0):
                    return
            pending = new_pending
            # a COMPLETED round closes the breaker window: `fails`
            # counts consecutive deaths, not lifetime deaths — an
            # always-on fleet absorbing an isolated death every few
            # hours must never creep toward permanent retirement
            if w.gen == my_gen and not w.fenced:
                w.fails = 0
        _abandon(pending)                   # stop flag: close() sweep
        #                                     resolves the jobs; the
        #                                     round still logs its span
        self._mark_exited(w, my_gen)        # stop flag: clean exit

    # ---------------------------------------------------------- failover

    def _supervise(self) -> None:
        """Heartbeat/lease monitor + backoff-gated respawner. Exits when
        the service stops, or when every slot has retired (circuit open
        fleet-wide — pending journal frames await recovery by a new
        process), or when a drain has fully completed."""
        svc = self.svc
        cfg = self.cfg
        while not svc._stop.is_set():
            time.sleep(cfg.supervise_poll_s)
            now = time.monotonic()
            for w in self._slots:
                if w.state == UP:
                    if w.thread is not None and not w.thread.is_alive():
                        self._declare_dead(w, "worker thread died")
                    elif now - w.last_beat > cfg.lease_s:
                        w.fenced = True   # zombie fence BEFORE requeue
                        self._declare_dead(
                            w, f"heartbeat lease ({cfg.lease_s:g} s) "
                               "missed — worker wedged")
                elif w.state == COOLDOWN and now >= w.rejoin_at:
                    if svc._draining.is_set() and svc._adm.empty() \
                            and self.inflight_total() == 0:
                        continue    # nothing left to rejoin for
                    self.log.warning(
                        "serve worker slot %d rejoining after backoff "
                        "(%d consecutive death(s))", w.slot, w.fails)
                    self._spawn(w)
            states = {w.state for w in self._slots}
            if not states & {UP, COOLDOWN}:
                # nothing left to monitor or respawn
                if RETIRED in states:
                    self.log.error(
                        "serve worker fleet circuit-open: every "
                        "non-exited slot exceeded max_worker_restarts="
                        "%d — pending requests stay journaled for "
                        "recovery by a new process",
                        cfg.max_worker_restarts)
                return
            if svc._draining.is_set() and svc._adm.empty() \
                    and self.inflight_total() == 0 \
                    and not any(w.thread is not None
                                and w.thread.is_alive()
                                for w in self._slots):
                return              # drain complete

    def _declare_dead(self, w: Worker, reason: str) -> None:
        """Declare one worker dead and make its loss routine: requeue
        every in-flight job to the surviving workers (through the
        checkpoint codec), open this slot's breaker, and re-derive the
        backpressure hint from what is left."""
        svc = self.svc
        with self._lock:
            w.fails += 1
            uid = w.uid
            retire = w.fails > self.cfg.max_worker_restarts
            w.state = RETIRED if retire else COOLDOWN
            if not retire:
                w.rejoin_at = time.monotonic() + delay_for(
                    self._rejoin_policy, min(w.fails - 1,
                                             self._rejoin_policy.attempts
                                             - 1))
            orphans, w.inflight = w.inflight, []
            self._rebuild_alive_view()
        svc.telemetry.gauge(
            "serve_worker_up", labels={"worker": str(w.slot)}).set(0)
        svc.telemetry.counter("serve_failover_total").inc()
        with svc._lock:
            svc.stats["failovers"] += 1
        self._publish_capacity()
        (self.log.error if retire else self.log.warning)(
            "serve worker %s declared dead (%s): %d in-flight job(s) "
            "to fail over; slot %s", uid, reason, len(orphans),
            "RETIRED (circuit open)" if retire
            else f"rejoins in {max(0.0, w.rejoin_at - time.monotonic()):.2f} s")
        svc._journal_event("failover", worker=uid, reason=reason,
                           orphans=len(orphans), retired=retire)
        # the dead worker cannot flush its own span ring (a SIGKILLed
        # or wedged thread leaves no atexit); the supervisor flushes on
        # its behalf so the spans LEADING UP to the death survive to
        # the journal (docs/OBSERVABILITY.md §swarmtrace)
        svc._flush_spans(f"worker {uid} declared dead: {reason}")
        # solo attribution for the poison bound, pipeline-aware: a kill
        # implicates a job only if it was ALONE in its own picked batch
        # AND it is the only such solo orphan (with the pipeline a dead
        # worker usually leaves TWO rounds' orphans — an orphan-set
        # "len == 1" test would let a poison request hide behind the
        # overlapping round's jobs forever, while blaming EVERY solo
        # orphan would let a poison kill implicate an innocent suspect
        # running its quarantine round in the overlapping slot; two
        # solos at once is ambiguous, and ambiguity quarantines but
        # never counts — the next unambiguous kill does).
        solos = [job for job, _ in orphans if job.pick_batch == 1]
        for job, epoch in orphans:
            svc._failover_job(job, epoch, uid,
                              solo=(job.pick_batch == 1
                                    and len(solos) == 1))
