"""swarmrouter — a stateless routing tier over process-per-worker
cells (docs/SERVICE.md §process mode).

`SwarmRouter` is the front door for a fleet of `serve.procworker`
processes. It speaks the SAME codec-framed wire protocol in both
directions and holds no durable state of its own — every promise lives
in a worker's per-slot journal, so the router can die and restart
without losing anything:

- **south side (supervision)**: a TCP listener procworkers dial. The
  HELLO carries ``(slot, incarnation, pid)`` and admission is the
  duplicate-claim arbiter — exactly one process owns a slot, the loser
  is refused with a structured error before it can build a service.
  Heartbeats are `wire.K_PING` frames; the lease/declare-dead logic
  from `serve.workers.WorkerPool` carries over with "thread death"
  replaced by *connection death OR process exit*, and fencing by
  per-job epochs replaced by incarnation-stamped journal frames
  (`service.write_fence` — stamped into the slot's journal dir before
  every respawn, so a zombie's writes are no-ops);
- **north side (clients)**: the router IS a `wire.WireServer` service
  facade — it implements the same four-member surface the wire server
  needs (``telemetry`` / ``stats`` / ``submit`` / ``cancel``), so the
  front door is the UNCHANGED wire protocol and any existing
  `WireClient` (the PR-13 traffic fleet included) talks to the fleet
  without knowing it is one;
- **placement**: rendezvous hash of ``(bucket, incarnation set)`` —
  the same `serve.workers.place_slot` math, with worker UIDs
  (``slot.gen``) as candidates, so churn re-places only the dead
  incarnation's buckets;
- **failover**: reconnect-attach through the journal. A killed
  process's slot respawns onto its STABLE journal dir; recovery
  re-admits the in-flight requests from their req-frames and resumes
  rollouts from their chunk checkpoints (bit-identical, the PR-8
  proof); the router re-submits the same request ids to the new
  incarnation and the service's idempotent attach binds them to the
  recovered jobs. The client's connection to the router never blinks;
- **rolling restart**: ``rolling_restart()`` drives
  drain → fence → respawn → re-admit per slot — the drill
  `benchmarks/router_fleet.py` runs under open-loop load with
  SIGKILLs composed in.
"""
from __future__ import annotations

import dataclasses
import json
import os
import signal
import subprocess
import sys
import threading
import time
import uuid
from pathlib import Path
from typing import Dict, List, Optional

from aclswarm_tpu.resilience import checkpoint as ckptlib
from aclswarm_tpu.interop import transport
from aclswarm_tpu.serve import wire
from aclswarm_tpu.serve.api import (COMPLETED, E_CANCELLED, E_DEADLINE,
                                    E_SHUTDOWN, FAILED, TIMED_OUT,
                                    RejectedError, Result, ServeError,
                                    Ticket)
from aclswarm_tpu.serve.service import bucket_of, write_fence
from aclswarm_tpu.serve.workers import place_slot
from aclswarm_tpu.utils.locks import OrderedLock
from aclswarm_tpu.telemetry import MetricsRegistry
from aclswarm_tpu.utils import get_logger

# slot states (the process-fleet analogue of serve.workers' lifecycle)
SPAWNING = "spawning"    # launched / admitted, not READY yet
UP = "up"                # ready: data-plane client connected, placeable
DRAINING = "draining"    # placeable set excludes it; in-flight finishing
DEAD = "dead"            # declared dead (conn death / exit / lease)
RETIRED = "retired"      # circuit open: max consecutive respawns burned


@dataclasses.dataclass
class RouterConfig:
    """Router knobs. ``journal_root`` holds one STABLE dir per slot
    (``w{slot}``) — stability across incarnations is what makes respawn
    recovery (and therefore failover) work."""

    journal_root: str
    slots: int = 2
    host: str = "127.0.0.1"
    lease_s: float = 5.0           # worker silent this long => dead
    handshake_s: float = 5.0       # accepted sock must HELLO within
    spawn_timeout_s: float = 180.0  # child boot: jax import + recovery
    #                                + warmup compile
    poll_s: float = 0.005
    respawn: bool = True
    max_respawns: int = 3          # CONSECUTIVE spawn failures/deaths
    #                                before a slot retires (reset by a
    #                                completed READY + first beat)
    drain_timeout_s: float = 30.0
    max_resubmits: int = 5         # per-request failover budget
    max_inflight: int = 512        # router-level admission cap
    scrape_timeout_s: float = 10.0  # health/stats fan-out budget
    # ServiceConfig overrides + warmup list shipped to every child:
    # {"service": {...}, "warm": [[kind, params], ...]}
    worker: dict = dataclasses.field(default_factory=dict)
    incarnation: int = 0           # the router's own identity in its
    #                                HELLO acks (it is not journaled)


@dataclasses.dataclass
class _ProcSlot:
    """One supervised worker-process slot (parent-side record)."""

    slot: int
    gen: int = 0
    state: str = DEAD
    pid: Optional[int] = None
    wire_port: Optional[int] = None
    proc: Optional[subprocess.Popen] = None
    chan: object = None            # supervision SocketChannel
    client: Optional[wire.WireClient] = None
    last_beat: float = 0.0         # monotonic
    t_spawn: float = 0.0
    deaths: int = 0                # consecutive (retire input)
    stop_requested: bool = False   # clean stop: skip auto-respawn
    stats: dict = dataclasses.field(default_factory=dict)

    @property
    def uid(self) -> str:
        return f"{self.slot}.{self.gen}"


@dataclasses.dataclass
class _Route:
    """Router-side record of one in-flight client request — everything
    needed to re-dispatch it if its worker process dies."""

    rid: str
    kind: str
    params: dict
    tenant: str
    deadline_s: Optional[float]
    trace_id: Optional[str]
    bucket: tuple
    front: Ticket
    t_submit: float                # wall clock
    backend: Optional[Ticket] = None
    uid: str = ""
    resubmits: int = 0
    cancelled: bool = False
    dispatching: bool = False      # single-flight guard: submit() and
    #                                the pump must never race a double
    #                                forget+submit for one rid


class SwarmRouter:
    """Stateless wire front door + process-fleet supervisor. Also the
    `WireServer` service facade: ``telemetry`` / ``stats`` /
    ``submit`` / ``cancel`` are exactly the four members the wire
    dispatcher touches."""

    def __init__(self, cfg: RouterConfig, log=None):
        self.cfg = cfg
        self.log = log or get_logger("serve.router")
        self.telemetry = MetricsRegistry()
        self.stats = {"workers": int(cfg.slots)}
        self.root = Path(cfg.journal_root)
        self.root.mkdir(parents=True, exist_ok=True)
        # key set fixed at construction (slots never add/remove), so
        # len()/iteration are lock-free; the _ProcSlot FIELDS are
        # mutated under _lock
        self._slots: Dict[int, _ProcSlot] = {
            i: _ProcSlot(slot=i) for i in range(max(1, cfg.slots))}
        self._routes: Dict[str, _Route] = {}        # guarded-by: _lock
        self._lock = OrderedLock("serve.router", registry=self.telemetry)
        self._closing = False
        self._stop = threading.Event()
        # death ledger: every declared death, with wall + monotonic
        # stamps so drills measure detection latency from the kill.
        # APPEND-only, appended under _lock; drills and status
        # snapshots read len()/[-1] lock-free (atomic in CPython,
        # staleness tolerated by design — polling loops must not
        # contend with the supervision path)
        self.deaths: List[dict] = []
        self._sup = transport.SocketListener(cfg.host, 0)
        self._pending_socks: List[tuple] = []
        self.wire: Optional[wire.WireServer] = None
        self.telemetry.gauge("router_workers_total").set(len(self._slots))
        self._sup_thread = threading.Thread(
            target=self._supervise, daemon=True, name="router-supervise")
        self._pump_thread = threading.Thread(
            target=self._pump, daemon=True, name="router-pump")

    # ------------------------------------------------------- lifecycle

    @property
    def supervision_address(self) -> tuple:
        return self._sup.address

    @property
    def tcp_address(self) -> Optional[tuple]:
        """Client-facing (host, port) — None until start(front=True)."""
        return self.wire.tcp_address if self.wire is not None else None

    def _journal_dir(self, slot: int) -> Path:
        return self.root / f"w{slot}"

    def start(self, spawn: bool = True, front: bool = True,
              extra_env: Optional[dict] = None) -> "SwarmRouter":
        """Launch supervision + pump threads, optionally spawn the
        fleet and open the client-facing wire listener. Split so tests
        can run a router that only ARBITRATES (spawn=False, front=False
        — external claimants dial the supervision port themselves)."""
        self._sup_thread.start()
        self._pump_thread.start()
        if spawn:
            with self._lock:
                for sl in self._slots.values():
                    self._spawn_locked(sl, extra_env=extra_env)
        if front:
            self.wire = wire.WireServer(self, base=None,
                                        tcp=(self.cfg.host, 0))
        return self

    def wait_ready(self, timeout: float = None) -> bool:
        """Block until every non-retired slot is UP (placeable)."""
        t_end = time.monotonic() + (timeout if timeout is not None
                                    else self.cfg.spawn_timeout_s)
        while time.monotonic() < t_end:
            with self._lock:
                states = [sl.state for sl in self._slots.values()]
            if states and all(s in (UP, RETIRED) for s in states) \
                    and any(s == UP for s in states):
                return True
            time.sleep(0.05)
        return False

    def close(self, timeout: float = 30.0) -> None:
        self._closing = True
        if self.wire is not None:
            self.wire.close()
        # resolve whatever is still routed — the promise ledger lives
        # in the worker journals, so a recovery can still honor these
        with self._lock:
            routes = list(self._routes.values())
            self._routes.clear()
        for r in routes:
            r.front._resolve(Result(
                request_id=r.rid, status=FAILED,
                error=ServeError(E_SHUTDOWN, "router closing"),
                trace_id=r.trace_id or ""))
        with self._lock:
            slots = list(self._slots.values())
        for sl in slots:
            self._stop_slot_locked_free(sl)
        t_end = time.monotonic() + timeout
        for sl in slots:
            if sl.proc is not None:
                try:
                    sl.proc.wait(max(0.1, t_end - time.monotonic()))
                except subprocess.TimeoutExpired:
                    self.log.error("worker w%s did not exit — SIGKILL",
                                   sl.uid)
                    try:
                        sl.proc.kill()
                        sl.proc.wait(5.0)
                    except OSError:
                        pass
        self._stop.set()
        self._sup_thread.join(5.0)
        self._pump_thread.join(5.0)
        for sl in slots:
            if sl.client is not None:
                try:
                    sl.client.close(bye=False)
                except OSError:
                    pass
            if sl.chan is not None:
                sl.chan.close()
        for chan, _ in self._pending_socks:
            chan.close()
        self._sup.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # ---------------------------------------------------- spawn / stop

    def _spawn_locked(self, sl: _ProcSlot,
                      extra_env: Optional[dict] = None) -> None:
        """Fence the predecessor, bump the incarnation, launch the
        child (caller holds the lock)."""
        sl.gen += 1
        sl.state = SPAWNING
        sl.pid = None
        sl.wire_port = None
        sl.stop_requested = False
        sl.t_spawn = time.monotonic()
        sl.last_beat = time.monotonic()
        jdir = self._journal_dir(sl.slot)
        jdir.mkdir(parents=True, exist_ok=True)
        # fence FIRST: from here the predecessor's journal writes are
        # no-ops even if the child takes seconds to boot
        write_fence(jdir, sl.gen)
        cmd = [sys.executable, "-m", "aclswarm_tpu.serve.procworker",
               "--slot", str(sl.slot), "--incarnation", str(sl.gen),
               "--supervisor",
               f"{self.cfg.host}:{self.supervision_address[1]}",
               "--journal-dir", str(jdir),
               "--config", json.dumps(self.cfg.worker)]
        # the child must import this package no matter the parent's cwd
        import aclswarm_tpu
        pkg_root = str(Path(aclswarm_tpu.__file__).resolve().parents[1])
        env = {**os.environ, **(extra_env or {})}
        env["PYTHONPATH"] = (pkg_root + os.pathsep + env["PYTHONPATH"]
                             if env.get("PYTHONPATH") else pkg_root)
        logf = open(jdir / f"proc.{sl.gen}.log", "ab")
        try:
            sl.proc = subprocess.Popen(
                cmd, stdout=logf, stderr=subprocess.STDOUT, env=env)
        finally:
            logf.close()
        self.telemetry.counter("router_spawns_total").inc()
        self.log.info("spawned w%s pid %d (journal %s)",
                      sl.uid, sl.proc.pid, jdir)

    def ensure_spawned(self, slot: int,
                       extra_env: Optional[dict] = None) -> None:
        with self._lock:
            sl = self._slots[slot]
            if sl.state in (SPAWNING, UP, DRAINING):
                return
            if sl.state == RETIRED:
                sl.deaths = 0       # explicit restart resets the breaker
            self._spawn_locked(sl, extra_env=extra_env)

    def drain_slot(self, slot: int) -> None:
        """Remove the slot from the placeable set; in-flight work keeps
        running. Tells the worker too (observable ack)."""
        with self._lock:
            sl = self._slots[slot]
            if sl.state != UP:
                return
            sl.state = DRAINING
        self._send_ctl(sl, "drain")

    def stop_slot(self, slot: int, kill: bool = False) -> Optional[int]:
        """Stop the slot's process: ``kill=True`` SIGKILLs it (the
        chaos path — supervision notices via connection death and the
        failover machinery runs); otherwise a clean ``die`` control.
        Returns the pid stopped (None if the slot had none)."""
        with self._lock:
            sl = self._slots[slot]
            pid = sl.pid if sl.pid is not None else (
                sl.proc.pid if sl.proc is not None else None)
            if not kill:
                sl.stop_requested = True
        if pid is None:
            return None
        if kill:
            try:
                os.kill(pid, signal.SIGKILL)
            except OSError:
                pass
        else:
            self._send_ctl(sl, "die")
        return pid

    def _stop_slot_locked_free(self, sl: _ProcSlot) -> None:
        sl.stop_requested = True
        if sl.chan is not None:
            try:
                sl.chan.send_bytes(wire._frame(wire.K_EVENT,
                                               {"ctl": "die"}))
                sl.chan.flush()
            except OSError:
                pass

    def _send_ctl(self, sl: _ProcSlot, ctl: str) -> None:
        if sl.chan is None:
            return
        try:
            sl.chan.send_bytes(wire._frame(wire.K_EVENT, {"ctl": ctl}))
            sl.chan.flush()
        except OSError as e:
            self.log.error("ctl %s to w%s failed: %s", ctl, sl.uid, e)

    # ------------------------------------------------- supervision loop

    def _supervise(self) -> None:
        while not self._stop.is_set():
            try:
                busy = self._supervise_pass()
            except Exception:      # noqa: BLE001 — supervisor must live
                self.log.exception("supervision pass failed — continuing")
                busy = False
            if not busy:
                time.sleep(self.cfg.poll_s)

    def _supervise_pass(self) -> bool:
        busy = False
        # accept + handshake-window the supervision socks
        while True:
            chan = self._sup.accept()
            if chan is None:
                break
            busy = True
            self._pending_socks.append((chan, time.monotonic()))
        now = time.monotonic()
        for entry in list(self._pending_socks):
            chan, t0 = entry
            try:
                raw = chan.recv_bytes()
            except OSError:
                self._pending_socks.remove(entry)
                chan.close()
                continue
            if raw is None:
                if now - t0 > self.cfg.handshake_s:
                    self._pending_socks.remove(entry)
                    chan.close()
                continue
            busy = True
            self._pending_socks.remove(entry)
            self._admit(chan, raw)
        # per-slot: drain frames, watch the process, enforce the lease
        with self._lock:
            slots = list(self._slots.values())
        for sl in slots:
            if sl.chan is not None:
                try:
                    while True:
                        raw = sl.chan.recv_bytes()
                        if raw is None:
                            break
                        busy = True
                        self._worker_frame(sl, raw)
                except OSError as e:
                    self._declare_dead(sl, f"connection death: {e}")
                    continue
            if sl.state in (SPAWNING, UP, DRAINING):
                if sl.proc is not None and sl.proc.poll() is not None:
                    self._declare_dead(
                        sl, f"process exit (rc {sl.proc.returncode})",
                        expected=sl.stop_requested
                        or sl.proc.returncode == 0)
                elif sl.state in (UP, DRAINING) and sl.chan is not None \
                        and now - sl.last_beat > self.cfg.lease_s:
                    # the lease starts at READY: a SPAWNING child is
                    # silent by design (jax import + warm compile) and
                    # bounded by spawn_timeout_s instead
                    self._declare_dead(
                        sl, f"lease ({self.cfg.lease_s:g} s) missed — "
                            "process wedged")
                elif sl.state == SPAWNING and \
                        now - sl.t_spawn > self.cfg.spawn_timeout_s:
                    self._declare_dead(
                        sl, f"never READY within "
                            f"{self.cfg.spawn_timeout_s:g} s")
            if sl.state == DEAD and self.cfg.respawn and sl.gen > 0 \
                    and not sl.stop_requested and not self._closing:
                if sl.deaths > self.cfg.max_respawns:
                    sl.state = RETIRED
                    self.log.error(
                        "slot %d RETIRED after %d consecutive deaths",
                        sl.slot, sl.deaths)
                    self._gauge_up()
                else:
                    with self._lock:
                        self._spawn_locked(sl)
                    self.telemetry.counter("router_respawns_total").inc()
                    busy = True
        return busy

    def _admit(self, chan, raw: bytes) -> None:
        """Supervision HELLO admission — the duplicate-slot arbiter.
        Exactly one claimant wins; the loser gets a structured error
        and its connection closed before it can build a service."""
        try:
            payload, man = ckptlib.loads(raw, chan.name)
        except ckptlib.CheckpointError as e:
            self.log.error("corrupt supervision HELLO: %s", e)
            chan.close()
            return
        if man.get("kind") != wire.K_HELLO \
                or payload.get("role") != "procworker":
            self.log.warning("non-procworker HELLO on the supervision "
                             "port — closed")
            chan.close()
            return
        slot_id = int(payload.get("slot", -1))
        inc = int(payload.get("incarnation", -1))
        pid = int(payload.get("pid", 0))

        def _refuse(err: str, **extra) -> None:
            # called with _lock RELEASED: the loser's socket may be
            # wedged, and a blocking send under the router lock would
            # stall the sweep/respawn path for the whole fleet (the
            # ack send below already follows the same rule)
            self.telemetry.counter("router_hello_refused_total").inc()
            self.log.warning("HELLO w%d.%d pid %d REFUSED: %s",
                             slot_id, inc, pid, err)
            try:
                chan.send_bytes(wire._frame(
                    wire.K_ERROR, {"error": err, "slot": slot_id,
                                   **extra}))
                chan.flush()
            except OSError:
                pass
            chan.close()

        refusal = None      # (err, extra) decided under the lock
        with self._lock:
            sl = self._slots.get(slot_id)
            if sl is None:
                refusal = (f"unknown slot {slot_id}", {})
            elif sl.chan is not None and sl.state in (SPAWNING, UP,
                                                      DRAINING):
                refusal = ("slot_taken",
                           {"owner": sl.uid, "owner_pid": sl.pid})
            elif inc < sl.gen:
                refusal = ("stale_incarnation", {"current": sl.gen})
            elif sl.proc is not None and sl.state == SPAWNING \
                    and pid != sl.proc.pid:
                refusal = ("slot_reserved", {"owner_pid": sl.proc.pid})
            else:
                sl.gen = inc
                sl.pid = pid
                sl.chan = chan
                sl.state = SPAWNING     # READY promotes to UP
                sl.last_beat = time.monotonic()
                if sl.proc is None:
                    # externally-launched claimant (spawn=False mode):
                    # its boot budget starts at admission — an
                    # unstamped t_spawn would read as an expired spawn
                    # window and insta-declare the winner dead
                    sl.t_spawn = time.monotonic()
        if refusal is not None:
            _refuse(refusal[0], **refusal[1])
            return
        try:
            chan.send_bytes(wire._frame(wire.K_HELLO_ACK, {
                "server": "router", "accepted": True,
                "pid": os.getpid(),
                "incarnation": int(self.cfg.incarnation),
                "lease_s": self.cfg.lease_s,
                "workers": len(self._slots)}))
            chan.flush()
        except OSError as e:
            self._declare_dead(sl, f"ack send failed: {e}")
            return
        self.log.info("admitted w%s pid %d", sl.uid, pid)

    def _worker_frame(self, sl: _ProcSlot, raw: bytes) -> None:
        try:
            payload, man = ckptlib.loads(raw, sl.chan.name)
        except ckptlib.CheckpointError as e:
            self.log.error("corrupt frame from w%s: %s", sl.uid, e)
            return
        sl.last_beat = time.monotonic()
        kind = man.get("kind")
        if kind == wire.K_PING:
            if payload.get("stats"):
                sl.stats = dict(payload["stats"])
            return
        if kind == wire.K_BYE:
            self._declare_dead(sl, "clean BYE", expected=True)
            return
        if kind == wire.K_EVENT and payload.get("event") == "ready":
            sl.wire_port = int(payload["wire_port"])
            try:
                client = wire.WireClient(
                    tcp=(self.cfg.host, sl.wire_port),
                    client_id=f"router-w{sl.uid}",
                    tenant="_router", hello_timeout_s=15.0)
            except OSError as e:
                self._declare_dead(sl, f"data plane dial failed: {e}")
                return
            old = sl.client
            sl.client = client
            if old is not None:
                try:
                    old.close(bye=False)
                except OSError:
                    pass
            sl.state = UP
            sl.deaths = 0           # completed boot resets the breaker
            self._gauge_up()
            self.log.info("w%s UP (pid %d, data plane :%d, ack pid=%s "
                          "incarnation=%s)", sl.uid, sl.pid,
                          sl.wire_port,
                          client.server_info.get("pid"),
                          client.server_info.get("incarnation"))
            return
        if kind == wire.K_EVENT and payload.get("event") == "draining":
            self.log.info("w%s draining acknowledged (%s in flight)",
                          sl.uid, payload.get("inflight"))
            return

    def _gauge_up(self) -> None:
        with self._lock:
            up = sum(1 for s in self._slots.values() if s.state == UP)
        self.telemetry.gauge("router_workers_up").set(up)

    def _declare_dead(self, sl: _ProcSlot, reason: str,
                      expected: bool = False) -> None:
        """Connection death OR process exit OR lease miss — the
        process-fleet spelling of `WorkerPool._declare_dead`. Requeues
        the dead incarnation's routes for re-dispatch (the journal owns
        the durable copy; the respawned incarnation recovers it)."""
        with self._lock:
            if sl.state in (DEAD, RETIRED):
                return
            uid = sl.uid
            sl.state = DEAD
            sl.deaths = 0 if expected else sl.deaths + 1
            chan, client = sl.chan, sl.client
            sl.chan = None
            sl.client = None
            requeued = 0
            for r in self._routes.values():
                if r.uid == uid and r.backend is not None \
                        and not r.backend.done:
                    r.backend = None
                    r.resubmits += 1
                    requeued += 1
            death = {"slot": sl.slot, "uid": uid, "pid": sl.pid,
                     "reason": reason, "expected": bool(expected),
                     "requeued": requeued,
                     "t_dead_wall": time.time(),
                     "t_dead_mono": time.monotonic()}
            self.deaths.append(death)
        (self.log.info if expected else self.log.error)(
            "worker w%s DEAD (%s) — %d in-flight route(s) requeued for "
            "re-dispatch through the journal", uid, reason, requeued)
        if not expected:
            self.telemetry.counter("router_worker_deaths_total").inc()
        if requeued:
            self.telemetry.counter("router_failovers_total").inc(requeued)
        self._gauge_up()
        if chan is not None:
            chan.close()
        if client is not None:
            try:
                client.kill()
            except OSError:
                pass

    # -------------------------------------------------- placement/pump

    def _placeable(self) -> List[_ProcSlot]:
        return [sl for sl in self._slots.values()
                if sl.state == UP and sl.client is not None
                and sl.client.alive]

    def _place(self, bucket: tuple) -> Optional[_ProcSlot]:
        """Rendezvous over ``(bucket, incarnation set)``: candidates
        are worker UIDs, so a respawn (new incarnation) re-rolls ONLY
        what the hash moves — the same minimal-churn property the
        thread fleet's bucket placement has."""
        with self._lock:
            cands = {sl.uid: sl for sl in self._placeable()}
        if not cands:
            return None
        uid = place_slot(bucket, sorted(cands))
        return cands[uid]

    def _pump(self) -> None:
        while not self._stop.is_set():
            try:
                busy = self._pump_pass()
            except Exception:      # noqa: BLE001 — pump must live
                self.log.exception("route pump pass failed — continuing")
                busy = False
            if not busy:
                time.sleep(0.002)

    def _pump_pass(self) -> bool:
        busy = False
        now = time.time()
        with self._lock:
            routes = list(self._routes.values())
        for r in routes:
            if r.front.done:
                with self._lock:
                    self._routes.pop(r.rid, None)
                continue
            if r.backend is None:
                # awaiting re-dispatch after a worker death (or the
                # first dispatch raced a churn window)
                if r.deadline_s is not None \
                        and now - r.t_submit > r.deadline_s:
                    self._resolve(r, Result(
                        request_id=r.rid, status=TIMED_OUT,
                        error=ServeError(
                            E_DEADLINE,
                            f"deadline ({r.deadline_s:g} s) passed "
                            "while awaiting a live worker"),
                        latency_s=now - r.t_submit,
                        trace_id=r.trace_id or ""))
                    busy = True
                    continue
                busy |= self._dispatch(r)
                continue
            # forward buffered chunk events (done captured FIRST —
            # same race discipline as wire._pump_results)
            done_now = r.backend.done
            if not done_now and not self._uid_live(r.uid):
                # safety net for the dispatch-vs-death window: a
                # backend ticket parked on a killed client never
                # resolves (kill() suppresses resolution), so a
                # pending route on a dead incarnation requeues here
                # even if `_declare_dead` raced past it
                r.backend = None
                r.resubmits += 1
                self.telemetry.counter("router_failovers_total").inc()
                busy = True
                continue
            while True:
                try:
                    ev = r.backend._events.get_nowait()
                except Exception:   # queue.Empty
                    break
                if ev is wire._TICKET_SENTINEL:
                    r.backend._events.put(wire._TICKET_SENTINEL)
                    break
                busy = True
                r.front._push(ev)
            if done_now:
                busy = True
                res = r.backend.result(timeout=0)
                if self._is_worker_loss(r, res) \
                        and r.resubmits <= self.cfg.max_resubmits \
                        and not r.cancelled and not self._closing:
                    # the backend died under the request: requeue — the
                    # journal still owes it, the respawn will recover it
                    r.backend = None
                    r.resubmits += 1
                    self.telemetry.counter(
                        "router_failovers_total").inc()
                    continue
                self._resolve(r, dataclasses.replace(
                    res, failovers=res.failovers + (1 if r.resubmits
                                                    else 0)))
        return busy

    def _uid_live(self, uid: str) -> bool:
        """Is this EXACT incarnation still serving (UP or finishing a
        drain) with a usable data-plane client?"""
        try:
            slot = int(uid.split(".")[0])
        except (ValueError, IndexError):
            return False
        with self._lock:
            sl = self._slots.get(slot)
            return (sl is not None and sl.state in (UP, DRAINING)
                    and sl.uid == uid and sl.client is not None
                    and sl.client.alive)

    def _is_worker_loss(self, r: _Route, res: Result) -> bool:
        """A terminal that means 'the WORKER went away', not 'the
        request failed': wire transport errors, or a shutdown the
        worker broadcast while dying. Only treated as loss when the
        placed incarnation is in fact no longer the live one —
        a healthy worker's genuine error result always passes
        through."""
        if res.error is None:
            return False
        if res.error.code not in ("wire_error", E_SHUTDOWN):
            return False
        return not self._uid_live(r.uid)

    def _dispatch(self, r: _Route) -> bool:
        with self._lock:
            if r.backend is not None or r.dispatching or r.cancelled \
                    or r.front.done:
                return False
            r.dispatching = True
        try:
            sl = self._place(r.bucket)
            if sl is None:
                return False
            client = sl.client
            try:
                client.forget(r.rid)    # a fresh ticket per dispatch
                backend = client.submit(
                    r.kind, r.params, request_id=r.rid,
                    tenant=r.tenant, deadline_s=r.deadline_s,
                    trace_id=r.trace_id)
            except OSError as e:
                self.log.error("dispatch %s to w%s failed: %s",
                               r.rid, sl.uid, e)
                return False
            with self._lock:
                r.backend = backend
                r.uid = sl.uid
            self.telemetry.counter("router_dispatch_total").inc()
            return True
        finally:
            with self._lock:
                r.dispatching = False

    def _resolve(self, r: _Route, res: Result) -> None:
        with self._lock:
            self._routes.pop(r.rid, None)
        r.front._resolve(res)

    # --------------------------------------- WireServer service facade

    def submit(self, kind: str, params: dict, *,
               tenant: str = "default",
               request_id: Optional[str] = None,
               deadline_s: Optional[float] = None,
               trace_id: Optional[str] = None) -> Ticket:
        """The `SwarmService.submit` surface, routing edition. The
        ticket returned is the ROUTER's promise: it survives worker
        process death (re-dispatch through the journal) and resolves
        with whatever terminal the fleet produces. ``health`` and
        ``stats`` are answered by fleet-wide aggregation — one scrape
        reads every process."""
        rid = request_id or uuid.uuid4().hex[:12]
        if self._closing:
            raise RejectedError(E_SHUTDOWN, 0.0)
        with self._lock:
            prior = self._routes.get(rid)
            if prior is not None:
                return prior.front  # idempotent duplicate attach
        if kind in ("health", "stats") \
                and not (params or {}).get("worker_only"):
            front = Ticket(rid)
            threading.Thread(
                target=self._scrape, daemon=True,
                args=(kind, dict(params or {}), front, rid),
                name=f"router-scrape-{rid}").start()
            return front
        bucket = bucket_of(kind, params or {})   # ValueError refuses
        with self._lock:
            if len(self._routes) >= self.cfg.max_inflight:
                raise RejectedError("router inflight cap", 0.25)
            if not any(sl.state in (UP, SPAWNING, DRAINING)
                       for sl in self._slots.values()):
                raise RejectedError("no live workers", 1.0)
            front = Ticket(rid)
            r = _Route(rid=rid, kind=kind, params=dict(params or {}),
                       tenant=tenant, deadline_s=deadline_s,
                       trace_id=trace_id, bucket=bucket, front=front,
                       t_submit=time.time())
            self._routes[rid] = r
        self.telemetry.counter("router_requests_total").inc()
        self._dispatch(r)           # pump retries if this window misses
        return front

    def cancel(self, request_id: str,
               reason: str = "cancelled by client"):
        """Wire-disconnect semantics at the router: resolve the front
        ticket with a structured ``cancelled`` error and drop the
        route. The worker-side copy runs to its own terminal and is
        discarded at ITS journal — bounded waste, never a wedge."""
        with self._lock:
            r = self._routes.get(request_id)
            if r is None or r.front.done:
                return None
            r.cancelled = True
            self._routes.pop(request_id, None)
            backend = r.backend
        verdict = ("resident" if backend is not None
                   and backend.accepted else "queued")
        r.front._resolve(Result(
            request_id=request_id, status=FAILED,
            error=ServeError(E_CANCELLED, reason),
            trace_id=r.trace_id or ""))
        return verdict

    # ----------------------------------------------- fleet aggregation

    def _scrape(self, kind: str, params: dict, front: Ticket,
                rid: str) -> None:
        """Fan a ``health``/``stats`` scrape across every live process
        and aggregate into ONE codec-serializable payload — the fleet
        is one scrape target (`telemetry/watch.py --tcp` pointed at the
        router sees every worker process, pids and incarnations
        included)."""
        t0 = time.time()
        with self._lock:
            live = [(sl.uid, sl.slot, sl.pid, sl.client)
                    for sl in self._placeable()]
            states = {sl.uid: sl.state for sl in self._slots.values()}
        per: Dict[str, dict] = {}
        for uid, slot, pid, client in live:
            sub = dict(params)
            sub["worker_only"] = True
            try:
                res = client.submit(
                    kind, sub, request_id=f"{rid}.w{slot}",
                    tenant="_router").result(
                        timeout=self.cfg.scrape_timeout_s)
                per[uid] = {"pid": pid, "up": res.ok,
                            "value": res.value,
                            "error": (res.error.to_row()
                                      if res.error else None)}
            except (OSError, TimeoutError) as e:
                per[uid] = {"pid": pid, "up": False, "value": None,
                            "error": {"code": "scrape_failed",
                                      "message": str(e)}}
        if kind == "health":
            value = self._aggregate_health(per, states)
        else:
            value = self._aggregate_stats(params, per)
        front._resolve(Result(request_id=rid, status=COMPLETED,
                              value=value, latency_s=time.time() - t0))

    def _aggregate_health(self, per: Dict[str, dict],
                          states: Dict[str, str]) -> dict:
        counts: Dict[str, float] = {}
        queue_depth = 0
        per_worker: Dict[str, bool] = {u: False for u in states}
        processes: Dict[str, dict] = {}
        watch_enabled = False
        for uid, row in per.items():
            h = row.get("value") or {}
            per_worker[uid] = bool(row.get("up")) and bool(
                h.get("alive", False))
            watch_enabled |= bool(h.get("watch_enabled"))
            queue_depth += int(h.get("queue_depth", 0))
            for k, v in (h.get("counts") or {}).items():
                counts[k] = counts.get(k, 0) + v
            processes[uid] = {
                "pid": h.get("pid", row.get("pid")),
                "incarnation": h.get("incarnation"),
                "up": per_worker[uid],
                "watch": h.get("watch"),
                "error": row.get("error")}
        up = sum(1 for v in per_worker.values() if v)
        with self._lock:
            inflight = len(self._routes)
        return {
            "t_wall": time.time(),
            "alive": up > 0,
            "pid": os.getpid(),
            "incarnation": int(self.cfg.incarnation),
            "router": True,
            "watch_enabled": watch_enabled,
            "watch": None,
            "workers": {"total": len(states), "up": up,
                        "per_worker": per_worker},
            "queue_depth": queue_depth,
            "inflight": inflight,
            "counts": counts,
            "deaths": len([d for d in self.deaths
                           if not d["expected"]]),
            "processes": processes,
        }

    def _aggregate_stats(self, params: dict,
                         per: Dict[str, dict]) -> dict:
        fmt = str(params.get("format", "prometheus"))
        if fmt == "prometheus":
            parts = [self.telemetry.prometheus_text()]
            for uid, row in sorted(per.items()):
                text = (row.get("value") or {}).get("text", "")
                parts.append(f"# process uid={uid} "
                             f"pid={row.get('pid')}\n{text}")
            return {"format": fmt, "text": "\n".join(parts)}
        return {"format": fmt, "router": self.telemetry.snapshot(),
                "pid": os.getpid(),
                "incarnation": int(self.cfg.incarnation),
                "workers": {uid: row.get("value")
                            for uid, row in sorted(per.items())}}

    # -------------------------------------------------- rolling restart

    def inflight_on(self, uid: str) -> int:
        with self._lock:
            return sum(1 for r in self._routes.values()
                       if r.uid == uid and r.backend is not None
                       and not r.backend.done)

    def route_uid(self, rid: str) -> str:
        """The incarnation a live route is currently placed on (empty
        when undispatched or already terminal) — lets a chaos drill
        aim its kill at the process actually carrying a request."""
        with self._lock:
            r = self._routes.get(rid)
            return r.uid if r is not None else ""

    def _wait_state(self, slot: int, want: str, timeout: float,
                    min_gen: int = 0) -> bool:
        t_end = time.monotonic() + timeout
        while time.monotonic() < t_end:
            with self._lock:
                sl = self._slots[slot]
                if sl.state == want and sl.gen >= min_gen:
                    return True
            time.sleep(0.02)
        return False

    def rolling_restart(self, kill: bool = False,
                        extra_env: Optional[dict] = None) -> List[dict]:
        """Drain → fence → respawn → re-admit, one slot at a time —
        the fleet never loses more than one cell of capacity. With
        ``kill=True`` the stop is a SIGKILL (the chaos drill: proves
        the drain→fence path needs no cooperation from the dying
        process); otherwise a clean ``die`` control. The fence is
        written by `_spawn_locked` before every respawn; re-admit is
        the successor's READY. Returns one row per slot with the
        measured detection/restart timings."""
        rows = []
        for slot in sorted(self._slots):
            with self._lock:
                sl = self._slots[slot]
                if sl.state == RETIRED:
                    continue
                old_uid, old_pid = sl.uid, sl.pid
            t0 = time.monotonic()
            self.drain_slot(slot)
            t_drain = time.monotonic()
            drained = True
            while self.inflight_on(old_uid) > 0:
                if time.monotonic() - t_drain > self.cfg.drain_timeout_s:
                    drained = False
                    break
                time.sleep(0.02)
            n_deaths = len(self.deaths)
            t_kill = time.monotonic()
            self.stop_slot(slot, kill=kill)
            # detection: the supervision loop notices (conn death /
            # exit) and declares — measured, not assumed
            detect_s = None
            t_end = time.monotonic() + self.cfg.lease_s + 10.0
            while time.monotonic() < t_end:
                if len(self.deaths) > n_deaths:
                    detect_s = self.deaths[-1]["t_dead_mono"] - t_kill
                    break
                time.sleep(0.005)
            self.ensure_spawned(slot, extra_env=extra_env)
            up = self._wait_state(slot, UP, self.cfg.spawn_timeout_s,
                                  min_gen=int(old_uid.split(".")[1]) + 1)
            with self._lock:
                sl = self._slots[slot]
                new_uid, new_pid = sl.uid, sl.pid
            rows.append({
                "slot": slot, "old_uid": old_uid, "new_uid": new_uid,
                "old_pid": old_pid, "new_pid": new_pid,
                "killed": bool(kill), "drained": drained,
                "detect_s": detect_s, "readmitted": bool(up),
                "restart_s": time.monotonic() - t0})
            self.log.info("rolling restart slot %d: %s -> %s "
                          "(detect %.3fs, total %.1fs)", slot, old_uid,
                          new_uid, detect_s or -1.0,
                          rows[-1]["restart_s"])
        return rows

    def kill_slot(self, slot: int, wait_up: bool = True,
                  timeout: Optional[float] = None) -> dict:
        """SIGKILL a worker process mid-flight (NO drain — the hard
        failover drill) and measure kill→declared-dead detection
        latency plus the in-flight routes migrated. Auto-respawn
        brings the slot back; with ``wait_up`` blocks until the
        successor is re-admitted."""
        with self._lock:
            sl = self._slots[slot]
            old_uid, old_pid = sl.uid, sl.pid
        n_deaths = len(self.deaths)
        n_failovers = self.telemetry.counter(
            "router_failovers_total").value
        t_kill = time.monotonic()
        self.stop_slot(slot, kill=True)
        detect_s = None
        death = None
        t_end = time.monotonic() + self.cfg.lease_s + 10.0
        while time.monotonic() < t_end:
            if len(self.deaths) > n_deaths:
                death = self.deaths[-1]
                detect_s = death["t_dead_mono"] - t_kill
                break
            time.sleep(0.002)
        up = True
        if wait_up:
            up = self._wait_state(
                slot, UP, timeout or self.cfg.spawn_timeout_s,
                min_gen=int(old_uid.split(".")[1]) + 1)
        with self._lock:
            sl = self._slots[slot]
            new_uid, new_pid = sl.uid, sl.pid
        # migrated: the failover-counter DELTA, not death["requeued"]
        # alone — when the data-plane client notices the dead socket
        # before _declare_dead runs, it resolves the backend tickets
        # with wire_error and the PUMP's worker-loss path does the
        # requeue (death["requeued"] reads 0 for a real migration).
        # Both paths increment router_failovers_total.
        migrated = int(self.telemetry.counter(
            "router_failovers_total").value - n_failovers)
        return {"slot": slot, "old_uid": old_uid, "old_pid": old_pid,
                "new_uid": new_uid, "new_pid": new_pid,
                "detect_s": detect_s,
                "migrated": max(migrated,
                                death["requeued"] if death else 0),
                "readmitted": bool(up)}

    # ------------------------------------------------------- inspection

    def fleet(self) -> List[dict]:
        with self._lock:
            return [{"slot": sl.slot, "uid": sl.uid, "state": sl.state,
                     "pid": sl.pid, "wire_port": sl.wire_port,
                     "deaths": sl.deaths, "stats": dict(sl.stats)}
                    for sl in self._slots.values()]

    def journal_dirs(self) -> List[Path]:
        """Every per-slot journal dir — the postmortem's input set
        (`telemetry.postmortem.fleet_reconstruct`)."""
        return [self._journal_dir(s) for s in sorted(self._slots)]
