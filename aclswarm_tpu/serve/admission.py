"""Admission control + tenant-fair scheduling (docs/SERVICE.md).

Two jobs, one lock:

- **Admission** is the only unbounded-growth defense the service has:
  per-tenant and global queue caps, enforced at `submit` time with an
  explicit `RejectedError` carrying a drain-rate-based ``retry_after_s``
  hint. A request the service cannot promise to run is refused at the
  door — never parked on an unbounded queue that turns deadlines into
  lies (the Orca/vLLM-style admission posture, PAPERS.md).
- **Fair pick**: the worker asks for the next batch of same-bucket jobs
  and gets them round-robin across tenants — the tenant cursor advances
  every pick, and batch slots are dealt one-per-tenant-per-cycle, so a
  tenant flooding its (bounded) queue can delay another tenant by at
  most one batch residency, never starve it. Within a tenant, FIFO.

A *bucket* is the shape-compatibility key (`service._Job.bucket`):
requests in one device batch must share it. The picker chooses the
bucket of the first eligible job at the cursor, then fills remaining
slots with same-bucket work from all tenants (fair cycle first, then
greedy) — heterogeneous traffic still packs, it just packs per-round.

Re-queueing (preempted or still-running-next-chunk jobs) bypasses the
caps: those requests were already accepted, and bouncing them would
convert backpressure into a silent loss.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

from aclswarm_tpu.serve.api import E_QUEUE_FULL, RejectedError


class AdmissionControl:
    """Bounded per-tenant FIFO queues with a round-robin batch picker.

    Thread-safety: every public method takes the one internal condition
    lock; `pick` blocks on it (bounded by ``timeout``) so the worker
    parks without spinning while the service is idle."""

    def __init__(self, max_per_tenant: int = 8, max_total: int = 32,
                 clock: Callable[[], float] = time.monotonic):
        self.max_per_tenant = int(max_per_tenant)
        self.max_total = int(max_total)
        self._cv = threading.Condition()
        self._queues: dict[str, list] = {}   # tenant -> FIFO of jobs
        self._order: list[str] = []          # tenant round-robin ring
        self._cursor = 0
        self._clock = clock
        # EWMA of per-request service time feeds the retry-after hint;
        # seeded pessimistically so an empty history still backs off
        self._ewma_s = 0.25

    # ------------------------------------------------------------- intake

    def admit(self, job, force: bool = False, hold: bool = False) -> None:
        """Enqueue an incoming job, enforcing the caps. ``force``
        bypasses them — recovery re-admission and preemption re-queues
        of ALREADY-accepted work must never bounce. ``hold`` enqueues
        the job *invisibly to the picker*: the slot counts toward the
        caps (so racing submits cannot oversubscribe) but the worker
        cannot start it until `release` — the journaled-service
        ordering gate (caps checked BEFORE the durable frame is
        written, frame durable before the worker can run the job)."""
        with self._cv:
            q = self._queues.setdefault(job.req.tenant, [])
            if job.req.tenant not in self._order:
                self._order.append(job.req.tenant)
            if not force:
                total = sum(len(x) for x in self._queues.values())
                if len(q) >= self.max_per_tenant:
                    raise RejectedError(
                        f"{E_QUEUE_FULL}: tenant {job.req.tenant!r} at "
                        f"its {self.max_per_tenant}-request cap",
                        self.retry_after())
                if total >= self.max_total:
                    raise RejectedError(
                        f"{E_QUEUE_FULL}: service at its "
                        f"{self.max_total}-request global cap",
                        self.retry_after())
            job.held = hold
            q.append(job)
            if not hold:
                self._cv.notify_all()

    def release(self, job) -> None:
        """Make a held job visible to the picker (its journal frame is
        durable — the acceptance promise now exists on disk)."""
        with self._cv:
            job.held = False
            self._cv.notify_all()

    def cancel(self, job) -> None:
        """Back out an enqueued-but-unpicked job (a failed submit):
        frees its caps slot. No-op if the job is not queued."""
        with self._cv:
            q = self._queues.get(job.req.tenant, [])
            if job in q:
                q.remove(job)

    def requeue(self, job) -> None:
        """Tail re-queue of an accepted job (next chunk / preempted)."""
        self.admit(job, force=True)

    # ------------------------------------------------------------ picking

    def pick(self, max_jobs: int, timeout: float) -> List:
        """Dequeue up to ``max_jobs`` same-bucket jobs, tenant-fair.
        Blocks up to ``timeout`` for work; [] = still idle."""
        deadline = self._clock() + timeout
        with self._cv:
            while True:
                lead = self._lead_job()
                if lead is not None:
                    break
                remaining = deadline - self._clock()
                if remaining <= 0 or not self._cv.wait(remaining):
                    if self._lead_job() is None:
                        return []
                    lead = self._lead_job()
                    break
            tenant, job0 = lead
            bucket = job0.bucket
            take = [job0]
            self._queues[tenant].remove(job0)
            # deal remaining slots one-per-tenant-per-cycle, starting
            # after the lead tenant; fall back to greedy same-bucket
            # fill once a full cycle adds nothing
            ring = self._order
            start = (ring.index(tenant) + 1) % len(ring)
            progress = True
            while len(take) < max_jobs and progress:
                progress = False
                for k in range(len(ring)):
                    if len(take) >= max_jobs:
                        break
                    t = ring[(start + k) % len(ring)]
                    j = next((x for x in self._queues.get(t, [])
                              if x.bucket == bucket and not x.held), None)
                    if j is not None:
                        self._queues[t].remove(j)
                        take.append(j)
                        progress = True
            # advance the cursor PAST the lead tenant: the next pick
            # starts from its neighbor (the fairness rotation)
            self._cursor = start
            return take

    def _lead_job(self):
        """(tenant, job) at the round-robin cursor, else None. Held
        jobs (mid-submit, journal frame not yet durable) are invisible."""
        ring = self._order
        for k in range(len(ring)):
            t = ring[(self._cursor + k) % len(ring)]
            j = next((x for x in self._queues.get(t, [])
                      if not x.held), None)
            if j is not None:
                return t, j
        return None

    # ---------------------------------------------------------- telemetry

    def pending(self) -> int:
        with self._cv:
            return sum(len(q) for q in self._queues.values())

    def pending_excluding(self, job) -> int:
        """Queued work besides ``job``'s own next-chunk re-queue — the
        preemption trigger (evicting with nobody waiting is pure tax)."""
        with self._cv:
            return sum(1 for q in self._queues.values() for x in q
                       if x is not job)

    def empty(self) -> bool:
        return self.pending() == 0

    def note_service(self, dt_s: float) -> None:
        """Fold one request's service time into the drain-rate EWMA."""
        with self._cv:
            self._ewma_s = 0.8 * self._ewma_s + 0.2 * max(0.0, dt_s)

    def retry_after(self) -> float:
        """Backpressure hint: estimated time for the current backlog to
        drain (EWMA service time x pending), clamped to [0.05, 30] s."""
        backlog = sum(len(q) for q in self._queues.values())
        return float(min(30.0, max(0.05, self._ewma_s * max(1, backlog))))

    def wake(self) -> None:
        """Nudge a parked `pick` (shutdown/drain transitions)."""
        with self._cv:
            self._cv.notify_all()
