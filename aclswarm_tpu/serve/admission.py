"""Admission control + tenant-fair scheduling (docs/SERVICE.md).

Two jobs, one lock:

- **Admission** is the only unbounded-growth defense the service has:
  per-tenant and global queue caps, enforced at `submit` time with an
  explicit `RejectedError` carrying a drain-rate-based ``retry_after_s``
  hint. A request the service cannot promise to run is refused at the
  door — never parked on an unbounded queue that turns deadlines into
  lies (the Orca/vLLM-style admission posture, PAPERS.md).
- **Fair pick**: the worker asks for the next batch of same-bucket jobs
  and gets them round-robin across tenants — the tenant cursor advances
  every pick, and batch slots are dealt one-per-tenant-per-cycle, so a
  tenant flooding its (bounded) queue can delay another tenant by at
  most one batch residency, never starve it. Within a tenant, FIFO.

A *bucket* is the shape-compatibility key (`service._Job.bucket`):
requests in one device batch must share it. The picker chooses the
bucket of the first eligible job at the cursor, then fills remaining
slots with same-bucket work from all tenants (fair cycle first, then
greedy) — heterogeneous traffic still packs, it just packs per-round.

Multi-worker serving (`serve.workers`) adds two hooks without changing
the fairness policy: `pick` takes an ``eligible`` predicate (each
worker only sees jobs whose bucket the placement function maps to it —
admission SHARDS buckets across workers) and an ``on_take`` callback
invoked under the queue lock before the picked batch is released (the
worker registers its in-flight set atomically with the dequeue, so the
supervisor can never observe jobs that are neither queued nor owned).
`set_capacity` re-derives the retry-after hint from SURVIVING capacity:
with half the workers dead the same backlog drains half as fast, and
the backpressure hint says so.

Re-queueing (preempted or still-running-next-chunk jobs) bypasses the
caps: those requests were already accepted, and bouncing them would
convert backpressure into a silent loss.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

from aclswarm_tpu.serve.api import E_QUEUE_FULL, RejectedError


class AdmissionControl:
    """Bounded per-tenant FIFO queues with a round-robin batch picker.

    Thread-safety: every public method takes the one internal condition
    lock; `pick` blocks on it (bounded by ``timeout``) so the worker
    parks without spinning while the service is idle."""

    def __init__(self, max_per_tenant: int = 8, max_total: int = 32,
                 clock: Callable[[], float] = time.monotonic):
        self.max_per_tenant = int(max_per_tenant)
        self.max_total = int(max_total)
        self._cv = threading.Condition()
        self._queues: dict[str, list] = {}   # tenant -> FIFO of jobs
        self._order: list[str] = []          # tenant round-robin ring
        self._cursor = 0
        self._clock = clock
        # EWMA of per-request service time feeds the retry-after hint;
        # seeded pessimistically so an empty history still backs off
        self._ewma_s = 0.25
        # surviving-capacity scale on the hint: total workers / alive
        # workers (1.0 single-worker; grows as workers die, capped in
        # retry_after; set by the worker-pool supervisor)
        self._capacity_scale = 1.0

    # ------------------------------------------------------------- intake

    def admit(self, job, force: bool = False, hold: bool = False) -> None:
        """Enqueue an incoming job, enforcing the caps. ``force``
        bypasses them — recovery re-admission and preemption re-queues
        of ALREADY-accepted work must never bounce. ``hold`` enqueues
        the job *invisibly to the picker*: the slot counts toward the
        caps (so racing submits cannot oversubscribe) but the worker
        cannot start it until `release` — the submit-side ordering
        gate: caps are checked BEFORE the durable journal frame is
        written, the frame is durable AND the request's batch-layout
        row is prepped (`serve.staging` submit-time prep — since PR 11
        every submit holds) before the worker can run the job."""
        with self._cv:
            q = self._queues.setdefault(job.req.tenant, [])
            if job.req.tenant not in self._order:
                self._order.append(job.req.tenant)
            if not force:
                total = sum(len(x) for x in self._queues.values())
                if len(q) >= self.max_per_tenant:
                    raise RejectedError(
                        f"{E_QUEUE_FULL}: tenant {job.req.tenant!r} at "
                        f"its {self.max_per_tenant}-request cap",
                        self.retry_after())
                if total >= self.max_total:
                    raise RejectedError(
                        f"{E_QUEUE_FULL}: service at its "
                        f"{self.max_total}-request global cap",
                        self.retry_after())
            job.held = hold
            q.append(job)
            if not hold:
                self._cv.notify_all()

    def release(self, job) -> None:
        """Make a held job visible to the picker (its journal frame is
        durable and its staging row is prepped — the acceptance
        promise exists on disk, and round-time pack owes this request
        only an index shuffle)."""
        with self._cv:
            job.held = False
            self._cv.notify_all()

    def cancel(self, job) -> bool:
        """Back out an enqueued-but-unpicked job (a failed submit, or a
        wire client dying with entries still queued): frees its caps
        slot. Returns True iff the job was queued here — False means it
        is resident in a worker batch (or already terminal) and must be
        cancelled at a chunk boundary instead, never mid-batch."""
        with self._cv:
            q = self._queues.get(job.req.tenant, [])
            if job in q:
                q.remove(job)
                return True
            return False

    def requeue(self, job) -> None:
        """Tail re-queue of an accepted job (next chunk / preempted)."""
        self.admit(job, force=True)

    def contains(self, job) -> bool:
        """Is this exact job object currently queued? The failover
        supervisor's idempotence check: a job a fenced worker already
        requeued at its boundary is SAFE — failing it over again would
        double-enqueue it (two copies in one batch, chunks executed
        twice, digest ruined)."""
        with self._cv:
            return any(job in q for q in self._queues.values())

    # ------------------------------------------------------------ picking

    def pick(self, max_jobs: int, timeout: float,
             eligible: Optional[Callable] = None,
             on_take: Optional[Callable] = None) -> List:
        """Dequeue up to ``max_jobs`` same-bucket jobs, tenant-fair.
        Blocks up to ``timeout`` for work; [] = still idle.

        ``eligible(job)`` restricts the view (worker-sharded picking:
        each worker sees only the buckets placed on it). ``on_take`` is
        called with the picked batch WHILE the queue lock is held — the
        atomic queued→in-flight handoff the failover supervisor relies
        on (a job is always either queued or registered in-flight,
        never invisible in between)."""
        ok = eligible if eligible is not None else (lambda j: True)
        deadline = self._clock() + timeout
        with self._cv:
            while True:
                lead = self._lead_job(ok)
                if lead is not None:
                    break
                remaining = deadline - self._clock()
                if remaining <= 0 or not self._cv.wait(remaining):
                    lead = self._lead_job(ok)
                    if lead is None:
                        return []
                    break
            tenant, job0 = lead
            bucket = job0.bucket
            take = [job0]
            self._queues[tenant].remove(job0)
            # suspect quarantine (docs/SERVICE.md §multi-worker): a job
            # that was in-flight at a worker death runs ALONE until a
            # surviving chunk exonerates it — if the next kill comes,
            # the solo batch implicates exactly one request (and orphans
            # no innocents); conversely an innocent batch-mate of a
            # scripted/co-incidental kill completes its solo round and
            # never rides to the poison bound
            suspect0 = bool(getattr(job0, "suspect", False))
            # deal remaining slots one-per-tenant-per-cycle, starting
            # after the lead tenant; fall back to greedy same-bucket
            # fill once a full cycle adds nothing
            ring = self._order
            start = (ring.index(tenant) + 1) % len(ring)
            progress = not suspect0
            while len(take) < max_jobs and progress:
                progress = False
                for k in range(len(ring)):
                    if len(take) >= max_jobs:
                        break
                    t = ring[(start + k) % len(ring)]
                    j = next((x for x in self._queues.get(t, [])
                              if x.bucket == bucket and not x.held
                              and not getattr(x, "suspect", False)
                              and ok(x)), None)
                    if j is not None:
                        self._queues[t].remove(j)
                        take.append(j)
                        progress = True
            # advance the cursor PAST the lead tenant: the next pick
            # starts from its neighbor (the fairness rotation)
            self._cursor = start
            if on_take is not None:
                on_take(take)
            return take

    def _lead_job(self, ok: Callable):
        """(tenant, job) at the round-robin cursor, else None. Held
        jobs (mid-submit, journal frame not yet durable) and jobs the
        caller's ``ok`` predicate excludes (placed on another worker)
        are invisible."""
        ring = self._order
        for k in range(len(ring)):
            t = ring[(self._cursor + k) % len(ring)]
            j = next((x for x in self._queues.get(t, [])
                      if not x.held and ok(x)), None)
            if j is not None:
                return t, j
        return None

    # ---------------------------------------------------------- telemetry

    def pending(self) -> int:
        with self._cv:
            return sum(len(q) for q in self._queues.values())

    def pending_excluding(self, job) -> int:
        """Queued work besides ``job``'s own next-chunk re-queue — the
        preemption trigger (evicting with nobody waiting is pure tax)."""
        with self._cv:
            return sum(1 for q in self._queues.values() for x in q
                       if x is not job)

    def empty(self) -> bool:
        return self.pending() == 0

    def note_service(self, dt_s: float) -> None:
        """Fold one request's service time into the drain-rate EWMA."""
        with self._cv:
            self._ewma_s = 0.8 * self._ewma_s + 0.2 * max(0.0, dt_s)

    def set_capacity(self, alive: int, total: int) -> None:
        """Re-derive the drain-rate hint from SURVIVING capacity
        (graceful degradation to fewer workers): the EWMA measured
        per-request service time against the then-alive worker set, so
        with ``alive`` of ``total`` workers up the same backlog drains
        ``total/alive`` times slower. ``alive=0`` pins the scale to the
        hint's ceiling — the honest answer while the circuit-broken
        fleet backs off toward rejoin."""
        with self._cv:
            if alive <= 0:
                self._capacity_scale = float("inf")
            else:
                self._capacity_scale = max(1.0, total / alive)

    def retry_after(self) -> float:
        """Backpressure hint: estimated time for the current backlog to
        drain (EWMA service time x pending, scaled by the surviving-
        capacity factor), clamped to [0.05, 30] s."""
        backlog = sum(len(q) for q in self._queues.values())
        est = self._ewma_s * max(1, backlog) * self._capacity_scale
        return float(min(30.0, max(0.05, est)))

    def wake(self) -> None:
        """Nudge a parked `pick` (shutdown/drain transitions)."""
        with self._cv:
            self._cv.notify_all()
