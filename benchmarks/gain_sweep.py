"""Gain-design benchmark sweep: the `matlab/Benchmark.m` equivalent.

The reference sweeps its SDP-vs-ADMM gain design over n in [60, 200]
(`Benchmark.m:18`: numAgt = round(linspace(60,200,15))) and commits no
results; here the sweep is runnable on real hardware and the artifact is
committed (`benchmarks/results/gain_sweep.json`). Two parts:

1. **Timing sweep** (device ADMM): per-solve wall time over n, complete
   and simform-style sparse graphs, chained-scan methodology (see
   bench.py: K distinct instances inside one jit amortize the ~100 ms
   remote-tunnel launch overhead; medians over reps).
2. **Quality sweep** (small n): spectral-gap ratio of the device ADMM
   gains vs the independent SDP oracle (`aclswarm_tpu.gains.sdp`, the
   reference's `solve_original_sdp` formulation) — the cross-validation
   the reference gets from running both MATLAB solvers side by side.

Run: python benchmarks/gain_sweep.py [--quick] [--full]
     [--out benchmarks/results/gain_sweep.json]
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent))

from scale import _median_time  # noqa: E402  (readback-synced timer)


def sweep(quick: bool = False, full: bool = False, out: str | None = None):
    import jax
    import jax.numpy as jnp
    from jax import lax

    from aclswarm_tpu import gains as gl
    from aclswarm_tpu.gains import sdp
    from aclswarm_tpu.harness import formgen

    rng = np.random.default_rng(0)
    results = []

    def emit(row):
        row = {**row, "device": jax.devices()[0].platform}
        results.append(row)
        print(json.dumps(row))

    # --- timing sweep (Benchmark.m:18 range) ---
    if full:
        sizes = [int(round(x)) for x in np.linspace(60, 200, 15)]
    elif quick:
        sizes = [60, 100]
    else:
        sizes = [60, 100, 150, 200]
    K = 2 if quick else 8
    reps = 2 if quick else 5
    for n in sizes:
        ptss = jnp.asarray(rng.normal(size=(K, n, 3)).astype(np.float32)
                           * 10)
        for tag, adj in (
                ("fc", np.ones((n, n)) - np.eye(n)),
                ("sparse", formgen.random_adjmat(
                    np.random.default_rng(n), n, fc=False))):
            nonedges = int(np.sum(np.triu(1 - adj, 1)))

            def chain(ptss, adj=adj, n=n):
                def body(c, pp):
                    return c + gl.solve_gains(
                        pp, adj, max_nonedges=max(n - 4, 1)).sum(), None
                return lax.scan(body, jnp.float32(0), ptss)[0]

            dt = _median_time(jax.jit(chain), ptss, K, reps)
            emit({"metric": f"admm_gain_n{n}_{tag}_ms",
                  "value": round(dt * 1e3, 3),
                  "unit": "ms", "n": n, "graph": tag,
                  "nonedges": nonedges, "chain_k": K})

    # --- quality sweep vs the independent SDP oracle ---
    # THREE solvers through one metric: the device ADMM, the SDP oracle,
    # and the faithful NumPy re-derivation of the reference's own ADMM
    # (`gains/reference.py`, `solver.cpp` semantics). The third column
    # dispositions the device's 0.79-0.88 gap ratio (round-3 weak #5):
    # if the reference algorithm lands in the same band, the gap is
    # inherent to ADMM-with-early-stopping vs a converged SDP, not a
    # device regression.
    from aclswarm_tpu.gains import reference as refadmm
    qsizes = [8, 12] if quick else [8, 12, 16, 20]
    iters = 400 if quick else 1200
    for n in qsizes:
        pts = rng.normal(size=(n, 3)) * 3.0
        adj = formgen.random_adjmat(np.random.default_rng(n + 1), n,
                                    fc=False).astype(float)
        _, nullity = sdp.kernel_basis(pts)
        t0 = time.perf_counter()
        A_sdp = sdp.solve_sdp_gains(pts, adj, iters=iters)
        t_sdp = time.perf_counter() - t0
        A_admm = np.asarray(gl.solve_gains(jnp.asarray(pts), adj))
        A_ref = refadmm.solve_gains(pts, adj)
        gap_sdp = sdp.spectral_gap(A_sdp, nullity)
        gap_admm = sdp.spectral_gap(A_admm, nullity)
        gap_ref = sdp.spectral_gap(A_ref, nullity)
        emit({"metric": f"gain_quality_n{n}_ratio",
              "value": round(gap_admm / max(gap_sdp, 1e-12), 4),
              "unit": "ratio", "n": n,
              "gap_admm": round(gap_admm, 5), "gap_sdp": round(gap_sdp, 5),
              "gap_reference_admm": round(gap_ref, 5),
              "reference_admm_ratio": round(
                  gap_ref / max(gap_sdp, 1e-12), 4),
              "device_vs_reference": round(
                  gap_admm / max(gap_ref, 1e-12), 4),
              "sdp_oracle_s": round(t_sdp, 2)})

    if out:
        path = Path(out)
        path.parent.mkdir(parents=True, exist_ok=True)
        with open(path, "a") as fh:
            for row in results:
                fh.write(json.dumps(row) + "\n")
        print(f"# appended {len(results)} rows to {path}")
    return results


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--full", action="store_true",
                    help="the reference's full 15-point size sweep")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    import os
    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
    sweep(args.quick, args.full, args.out)


if __name__ == "__main__":
    main()
