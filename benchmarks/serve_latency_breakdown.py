"""serve_latency_breakdown — where a serve round's wall time goes
(docs/OBSERVABILITY.md §swarmtrace; ROADMAP item 2(f)'s evidence tool).

`serve_throughput.json` showed the ceiling (107 req/s at occupancy
1.0 on this host); this artifact shows what to attack: every
`serve.round` is split into pack / stack / dispatch / device-sync /
unpack / resolve child spans (`serve.service._rollout_round`), each
auto-feeding a `span_serve.round.<stage>_s` histogram in the service's
swarmscope registry. This benchmark drives a steady saturating load
through one service and commits one row per stage: count, mean,
p50/p95/p99, total seconds, and the stage's fraction of total round
wall — the per-stage latency breakdown a throughput attack starts
from.

Run:

    JAX_PLATFORMS=cpu python benchmarks/serve_latency_breakdown.py \
        [--quick] [--out benchmarks/results/serve_latency_breakdown.json]

Rows are schema-guarded by `benchmarks/check_results.py
::check_serve_latency_breakdown` (exact key set; the full stage set
must be present; child stages must sum to no more than the round).
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

RESULTS = Path(__file__).resolve().parent / "results"

N = 5
TICKS = 60                  # 3-chunk requests: rounds refill and pack
STAGES = ("pack", "stack", "dispatch", "device_sync", "unpack",
          "resolve")


def _drive(requests: int, start: bool = True):
    from aclswarm_tpu.serve import ServiceConfig, SwarmService

    svc = SwarmService(ServiceConfig(max_batch=4, quantum_chunks=4,
                                     idle_poll_s=0.01), start=start)
    tickets = [svc.submit("rollout",
                          {"n": N, "ticks": TICKS, "chunk_ticks": 20,
                           "seed": 1 + i},
                          tenant=f"t{i % 3}") for i in range(requests)]
    if not start:
        svc.start()
    for t in tickets:
        assert t.result(timeout=600).ok
    svc.close()
    return svc


def run_load(requests: int) -> object:
    # warm pass on THROWAWAY services: the jit cache is process-wide,
    # so compile every shape the measured load can reach BEFORE its
    # histograms start recording — the committed breakdown is the
    # steady state, not the compile storm. Queueing B requests before
    # start() guarantees the first round packs exactly
    # min(B, max_batch) (a started service drains too fast to reach
    # the bigger shapes deterministically). b=1,2,4 cover the
    # power-of-two batch shapes; b=12 overflows the fixed-capacity
    # staging store so the LRU-eviction path (serve.staging take_row
    # at store capacity) is compiled too.
    for b in (1, 2, 4, 12):
        _drive(b, start=False)
    return _drive(requests)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true",
                    help="fewer requests (CI smoke; artifact not "
                         "committed)")
    ap.add_argument("--out",
                    default=str(RESULTS / "serve_latency_breakdown.json"),
                    help="artifact path ('' to skip writing)")
    args = ap.parse_args(argv)

    import jax
    t0 = time.time()
    svc = run_load(6 if args.quick else 20)
    backend = jax.default_backend()

    def _row_of(stage: str, hist_name: str, round_sum: float) -> dict:
        h = svc.telemetry.histogram(hist_name).to_row()
        count = int(h.get("count", 0))
        total = float(h.get("sum", 0.0))
        return {
            "name": "serve_stage",
            "stage": stage,
            "n": N,
            "backend": backend,
            "count": count,
            "value": round(total / count, 6) if count else 0.0,
            "unit": "s",
            "p50_s": round(float(h.get("p50", 0.0)), 6),
            "p95_s": round(float(h.get("p95", 0.0)), 6),
            "p99_s": round(float(h.get("p99", 0.0)), 6),
            "sum_s": round(total, 6),
            "frac_round": round(total / round_sum, 4) if round_sum
            else 0.0,
            "quick": bool(args.quick),
        }

    round_row = svc.telemetry.histogram("span_serve.round_s").to_row()
    round_sum = float(round_row.get("sum", 0.0))
    rows = [_row_of("round", "span_serve.round_s", round_sum)]
    rows += [_row_of(s, f"span_serve.round.{s}_s", round_sum)
             for s in STAGES]
    child_sum = sum(r["sum_s"] for r in rows[1:])
    for r in rows:
        print(json.dumps(r), flush=True)
    print(f"# round wall {round_sum:.3f}s, child stages sum "
          f"{child_sum:.3f}s ({child_sum / round_sum:.1%} attributed), "
          f"{time.time() - t0:.1f}s total")
    if not all(r["count"] > 0 for r in rows):
        print("FAIL: a stage histogram recorded no observations")
        return 1
    if child_sum > round_sum * 1.001:
        print("FAIL: child stages sum past the round wall — the spans "
              "are mis-nested")
        return 1
    if args.out:
        p = Path(args.out)
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
        print(f"wrote {p}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
