"""Flood-merge tile sweep: the n=2000 single-chip squeeze (round-4 #4).

The flooded tick at n=2000 is the one metric below the 100 Hz bar
(41 Hz, `scale_tpu_n2000.json`), and phasing stopped helping because the
Pallas merge's shared ``packed`` block (N, W) re-streams from HBM once
per receiver tile — N/TV grid steps x the whole stripe. At n=1000 that
is 128 x 4 MB (tolerable next to compute); at n=2000 it is 256 x 8.4 MB
per stripe and the kernel goes HBM-bound. The sweep measures the merge
at alternative (TV receiver-tile, WC sender-chunk) shapes — larger TV
cuts the reload count linearly while the (TV, WC, W) candidate
temporary must stay inside VMEM — plus stripe widths (phases), then
re-measures the full engine flooded tick at the winner.

Run (real chip):  python benchmarks/flood_sweep.py [--n 2000]
Appends one JSON line per variant to benchmarks/results/flood_sweep.json.
"""
from __future__ import annotations

import argparse
import itertools
import json
import sys
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent))  # scale import

from aclswarm_tpu.utils.timing import timing_stats

RESULTS = Path(__file__).resolve().parent / "results"


def sweep(n: int, reps: int = 3, out: str | None = None) -> list:
    import jax
    import jax.numpy as jnp
    from jax import lax

    from aclswarm_tpu.ops._vmem import VMEM_BUDGET_BYTES
    from aclswarm_tpu.ops.flood_pallas import (flood_merge_bytes,
                                               flood_merge_pallas)

    rng = np.random.default_rng(0)
    rows = []

    def emit(row):
        rows.append(row)
        print(json.dumps(row), flush=True)
        if out:
            Path(out).parent.mkdir(parents=True, exist_ok=True)
            with open(out, "a") as fh:
                fh.write(json.dumps(row) + "\n")

    comm = jnp.asarray(
        (rng.random((n, n)) < 0.9).astype(np.float32))
    ages = rng.integers(0, 100, size=(n, n)).astype(np.int32)
    ids = np.arange(n, dtype=np.int32)

    # chained merges (distinct inputs) amortize the ~100 ms dispatch
    # floor; K sized so one dispatch stays well under the tunnel watchdog
    K = 8
    for phases in (1, 2, 4):
        w = -(-n // phases)
        packed_np = ((np.minimum(ages[:, :w], (1 << 15) - 1) << 16)
                     | ids[:, None])
        packs = jnp.asarray(                     # distinct ages: the age
            np.stack([packed_np + (k << 16)      # field is the HIGH half
                      for k in range(K)]))       # of the packed value

        for tv, wc in itertools.product((8, 16, 32, 64), (32, 64, 128)):
            need = flood_merge_bytes(n, w, tv, wc)
            if need > VMEM_BUDGET_BYTES:
                continue
            from aclswarm_tpu.ops._vmem import pad128
            if pad128(n) % tv or pad128(n) % wc:
                continue

            def chain(ps, tv=tv, wc=wc):
                def body(c, pk):
                    r = flood_merge_pallas(pk, comm, tv=tv, wc=wc)
                    return c + r.sum(), None
                return lax.scan(body, jnp.int32(0), ps)[0]

            try:
                jfn = jax.jit(chain)
                stats = timing_stats(jfn, packs, per=K, reps=reps)
            except Exception as e:       # Mosaic may reject a shape
                emit({"metric": f"flood_merge_n{n}_w{w}_tv{tv}_wc{wc}",
                      "error": str(e)[:200]})
                continue
            dt = stats["median_s"]
            emit({"metric": f"flood_merge_n{n}_w{w}_tv{tv}_wc{wc}",
                  "value": round(dt * 1e3, 3), "unit": "ms/stripe-merge",
                  "phases": phases,
                  "full_merge_ms": round(dt * phases * 1e3, 3),
                  "vmem_mb": round(need / 2**20, 1),
                  "spread_s": [round(stats["min_s"], 6),
                               round(stats["max_s"], 6)]})
    return rows


def tick_with(n: int, phases: int, reps: int, ticks: int = 60,
              out: str | None = None) -> dict:
    """Full engine flooded tick at the chosen phasing (the metric that
    must clear the bar) — the SAME problem builder as scale.py's
    flooded rows (`scale.build_bench_problem`), so this row is an
    apples-to-apples re-measurement under the same metric name."""
    import jax

    from aclswarm_tpu import sim
    from aclswarm_tpu.core.types import ControlGains
    from scale import build_bench_problem

    rng = np.random.default_rng(0)
    f, sp, _, k_ca, B = build_bench_problem(n, rng)
    st = sim.init_state(
        rng.normal(size=(n, 3)).astype(np.float32) * 20 + [0, 0, 2],
        localization=True)
    cfg = sim.SimConfig(assignment="none", localization="flooded",
                        flood_block=B, colavoid_neighbors=k_ca,
                        flood_phases=phases)
    roll = jax.jit(lambda s: sim.rollout(s, f, ControlGains(), sp, cfg,
                                         ticks)[0])
    stats = timing_stats(roll, st, per=ticks, reps=reps)
    dt = stats["median_s"]
    ca_tag = f"_k{k_ca}" if k_ca is not None else ""
    btag = f"_b{B}" if B else ""
    row = {"metric": f"flooded_tick_n{n}{ca_tag}{btag}_phased{phases}_hz",
           "value": round(1.0 / dt, 3), "unit": "Hz",
           "vs_baseline": round(1.0 / dt / 100.0, 2),
           "spread_s": [round(stats["min_s"], 6),
                        round(stats["max_s"], 6)]}
    print(json.dumps(row), flush=True)
    if out:
        with open(out, "a") as fh:
            fh.write(json.dumps(row) + "\n")
    return row


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n", type=int, default=2000)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--out", default=str(RESULTS / "flood_sweep.json"))
    ap.add_argument("--tick-phases", type=int, default=None,
                    help="also measure the full flooded tick at this "
                         "phasing")
    args = ap.parse_args(argv)
    sweep(args.n, args.reps, args.out)
    if args.tick_phases:
        tick_with(args.n, args.tick_phases, args.reps, out=args.out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
