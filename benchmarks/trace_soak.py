"""trace_soak — the swarmtrace acceptance artifact: a traced re-run of
the multi-worker kill soak, audited by POSTMORTEM RECONSTRUCTION
(docs/OBSERVABILITY.md §swarmtrace; ISSUE 9 acceptance bar).

Phase A (chaos, traced): the same request mix as
`serve_multiworker_soak.py` — three tenants, two rollout shape buckets
(several carrying FaultSchedules), single-shot assignment/gain work,
one deliberately poisoned request — into an N=3-worker journaled
service while scripted `CrashPlan`s repeatedly kill individual workers
mid-batch. Then the audit: **every accepted request — including the
killed, migrated, and poisoned ones — must reconstruct from the
on-disk journal alone** (`telemetry.postmortem`) **to a complete,
causally-ordered, gap-free timeline**: submitted → resolved with no
chunk-coverage holes, bit-identical digests on any re-executed chunk,
and one trace_id on every record across worker incarnations.

Overhead: the serve-path tracing tax is measured DIRECTLY on the
traced soak — the wall seconds spent inside `LifecycleLog.emit`
(accumulated per append, `lifecycle.LifecycleLog.spent_s`) divided by
the serve-path round wall (the ``span_serve.round_s`` histogram's
sum). A whole-run A/B cannot resolve a 2% bar through scheduler noise
on sub-second walls; the direct ratio can, and it measures the soak
itself rather than a proxy workload. Must stay under the 2% bar. (The
compiled surface is untouched either way: tracing is host-side only,
and the HLO zero-cost baseline is separately enforced by
`scripts/check.sh`; `ServiceConfig.trace=False` remains the ops
kill-switch.)

Run:

    JAX_PLATFORMS=cpu python benchmarks/trace_soak.py \
        [--quick] [--out benchmarks/results/trace_soak.json]

Exit 1 on any broken promise; the exact-key-set schema (acceptance
bars included) is enforced by `benchmarks/check_results.py
::check_trace_soak`.
"""
from __future__ import annotations

import argparse
import json
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent))

from serve_multiworker_soak import TENANTS, WORKERS, request_mix  # noqa: E402

RESULTS = Path(__file__).resolve().parent / "results"

OVERHEAD_BAR = 0.02


def run_chaos(quick: bool) -> tuple[dict, list[str]]:
    from aclswarm_tpu.resilience import InjectedCrash, arm_many
    from aclswarm_tpu.resilience.crash import CrashPlan
    from aclswarm_tpu.serve import (ServiceConfig, SwarmService,
                                    bucket_of, place_slot)
    from aclswarm_tpu.telemetry import postmortem

    problems: list[str] = []
    mix = request_mix(quick)
    roll_specs = [s for s in mix if s["kind"] == "rollout"]

    with tempfile.TemporaryDirectory(prefix="aclswarm_trace_soak_") as d:
        svc = SwarmService(ServiceConfig(
            workers=WORKERS, max_batch=2, quantum_chunks=1,
            max_queue_per_tenant=6, max_queue_total=24, journal_dir=d,
            supervise_poll_s=0.02, rejoin_base_s=0.05, rejoin_max_s=0.5,
            max_worker_restarts=8))

        def poison(params):
            raise InjectedCrash("poisoned request: kills its worker")

        svc.register("poison", poison)

        slots = list(range(WORKERS))
        slot5 = place_slot(bucket_of("rollout", roll_specs[0]["params"]),
                           slots)
        slot8 = place_slot(bucket_of("rollout", roll_specs[2]["params"]),
                           slots)
        plans = [CrashPlan(f"serve.w{slot5}", 2, "raise"),
                 CrashPlan(f"serve.w{slot5}", 5, "raise")]
        if slot8 != slot5:
            plans.append(CrashPlan(f"serve.w{slot8}", 3, "raise"))
        arm_many(plans)

        tickets = [(s, svc.submit(s["kind"], s["params"],
                                  tenant=s["tenant"],
                                  request_id=s["request_id"]))
                   for s in mix]
        tickets.append((
            {"kind": "poison", "tenant": "gamma",
             "request_id": "g-poison"},
            svc.submit("poison", {}, tenant="gamma",
                       request_id="g-poison")))
        results = {s["request_id"]: t.result(timeout=900)
                   for s, t in tickets}
        arm_many([])
        stats = dict(svc.stats)
        # direct overhead measurement off THIS soak: seconds spent
        # appending lifecycle events (the public ServeStats census)
        # over the serve-path round wall
        trace_spent = float(svc.serve_stats().trace_spent_s)
        round_wall = float(svc.telemetry.histogram(
            "span_serve.round_s").to_row().get("sum", 0.0))
        overhead = trace_spent / round_wall if round_wall else 0.0
        svc.close()

        # ---- the audit: reconstruct from DISK alone -------------------
        report = postmortem.reconstruct(d)
        accepted = len(tickets)
        if report["accepted"] != accepted:
            problems.append(f"journal shows {report['accepted']} "
                            f"acceptance frames for {accepted} submits")
        if report["reconstructed"] < accepted:
            problems.append(
                f"only {report['reconstructed']}/{accepted} requests "
                "reconstructed")
        dup_chunks = 0
        for rid, rep in report["requests"].items():
            dup_chunks += rep["duplicate_chunks"]
            if not (rep["complete"] and rep["gap_free"]):
                problems.append(
                    f"{rid}: timeline not complete+gap-free: "
                    f"{rep['problems'] or 'incomplete'}")
            res = results.get(rid)
            if res is not None and rep["trace_id"] != res.trace_id:
                problems.append(f"{rid}: journal trace {rep['trace_id']}"
                                f" != result trace {res.trace_id}")
            if res is not None and rep.get("status") != res.status:
                problems.append(f"{rid}: journal terminal status "
                                f"{rep.get('status')} != {res.status}")
        migrated = sum(1 for r in results.values() if r.failovers > 0)
        if stats["failovers"] < 1:
            problems.append("no worker was ever killed — the soak "
                            "proves nothing")
        if migrated < 1:
            problems.append("no request ever migrated workers")
        pres = results["g-poison"]
        if not (pres.status == "failed" and pres.error
                and pres.error.code == "poisoned"):
            problems.append("the poisoned request did not terminate "
                            "with the structured poisoned error")
        statuses = [r.status for r in results.values()]
        row = {
            "accepted": accepted,
            "completed": statuses.count("completed"),
            "timed_out": statuses.count("timed_out"),
            "failed": statuses.count("failed"),
            "worker_kills": int(stats["failovers"]),
            "migrated": migrated,
            "poisoned": int(stats["poisoned"]),
            "reconstructed": int(report["reconstructed"]),
            "complete": int(report["complete"]),
            "gap_free": int(report["gap_free"]),
            "timeline_events": int(report["events"]),
            "duplicate_chunks": int(dup_chunks),
            "trace_overhead_frac": round(overhead, 5),
        }
    return row, problems


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true",
                    help="smaller mix (CI smoke; artifact not "
                         "committed)")
    ap.add_argument("--out", default=str(RESULTS / "trace_soak.json"),
                    help="artifact path ('' to skip writing)")
    args = ap.parse_args(argv)

    t_start = time.time()
    chaos, problems = run_chaos(args.quick)
    if chaos["trace_overhead_frac"] >= OVERHEAD_BAR:
        problems.append(
            f"serve-path tracing overhead "
            f"{chaos['trace_overhead_frac']:.2%} >= {OVERHEAD_BAR:.0%} "
            "acceptance bar")

    import jax
    row = {
        "name": "trace_soak",
        "n": 8,                     # largest rollout shape in the mix
        "backend": jax.default_backend(),
        "workers": WORKERS,
        "tenants": len(TENANTS),
        **chaos,
        "wall_s": round(time.time() - t_start, 1),
        "quick": bool(args.quick),
    }
    print(json.dumps(row, indent=1))
    if problems:
        print(f"TRACE SOAK FAILED ({len(problems)} broken promise(s)):")
        for p in problems:
            print(f"  {p}")
        return 1
    if args.out:
        p = Path(args.out)
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(json.dumps(row, indent=1) + "\n")
        print(f"wrote {p}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
