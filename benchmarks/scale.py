"""Scale benchmarks: the north-star numbers (BASELINE.md) on real hardware.

Prints one JSON line per metric and (with --out) appends them to a results
file for committing as artifacts.

Methodology (pinned, see also bench.py): every metric chains K *distinct*
problem instances inside one jitted `lax.scan`, so numbers are sustained
per-instance throughput, immune to both dispatch-dedupe and the ~100 ms
fixed per-executable-launch overhead this environment's remote-TPU tunnel
adds (which would dominate any single-shot measurement; single-shot latency
is reported separately as *_latency_ms for honesty). Medians of `reps`
repeats.

Run: python benchmarks/scale.py [--n 1000] [--quick] [--sharded]
     [--out benchmarks/results/scale.json]
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


from aclswarm_tpu.utils.timing import readback_sync as _sync  # noqa: F401
from aclswarm_tpu.utils.timing import timing_stats as _timing_stats
# (single home: aclswarm_tpu/utils/timing.py — readback sync because
# block_until_ready is unreliable through the device tunnel, chained
# instances because of the ~108 ms fixed launch floor)

# per-call spread of the most recent _median_time, for the artifact's
# jitter columns (min/max over reps; a lone median hides tunnel hiccups)
_LAST_SPREAD: dict = {}


def _median_time(fn, arg, per: int, reps: int) -> float:
    stats = _timing_stats(fn, arg, per=per, reps=reps)
    _LAST_SPREAD.clear()
    _LAST_SPREAD.update(stats)
    return stats["median_s"]


# v5e single-chip peaks (public specs): 197 TFLOP/s bf16 on the MXU and
# 819 GB/s HBM bandwidth. The fractions below are *roofline positions*,
# not efficiency grades — these kernels are f32 elementwise/reduction
# dominated (VPU + HBM), so hbm_frac_peak is the binding axis for most of
# them and mxu_frac is expected to be small; the point is attributable
# regressions (a kernel that loses Hz shows WHERE: FLOP/s or GB/s).
V5E_PEAK_BF16_FLOPS = 197e12
V5E_HBM_BPS = 819e9


def _roofline(jfn, arg, dt: float, per: int = 1,
              pallas_flops: float = 0.0) -> dict:
    """Achieved FLOP/s + HBM GB/s from XLA's compiled cost analysis.

    ``jfn`` must be the jitted callable that was timed, ``arg`` its input,
    ``dt`` the measured per-instance seconds, ``per`` the instances per
    call (chained scans). Uses `Compiled.cost_analysis()` — XLA's static
    estimate of flops and bytes accessed. That estimate under-reports
    iterative kernels on EVERY routing (round-4 review Weak #1: the
    headline row published 0.1 GFLOP/s for ~10^9 flops): Pallas bodies
    are opaque custom calls, and XLA scan/while loop bodies are counted
    once rather than per trip. Callers therefore pass ``pallas_flops`` —
    the per-instance analytic count from the kernel's `analytic_flops`,
    regardless of which impl the routing picked — and it is ADDED to the
    XLA figure; rows carrying it are tagged
    `flops_model: "xla+analytic"` (the tag marks the counting model, NOT
    that the Pallas kernel ran). The HBM number stays XLA's: it covers
    custom-call operand traffic (and VMEM-resident kernels move nothing
    else). Returns {} where the backend offers no analysis."""
    try:
        ca = jfn.lower(arg).compile().cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        flops = float(ca.get("flops", 0.0)) / per + float(pallas_flops)
        byts = float(ca.get("bytes accessed", 0.0)) / per
        if flops <= 0.0 and byts <= 0.0:
            return {}
        row = {"flops_per_instance": round(flops),
               "achieved_gflops_s": round(flops / dt / 1e9, 1),
               "hbm_gb_s": round(byts / dt / 1e9, 1),
               "mxu_frac_bf16peak": round(
                   flops / dt / V5E_PEAK_BF16_FLOPS, 5),
               "hbm_frac_peak": round(byts / dt / V5E_HBM_BPS, 4)}
        if pallas_flops > 0.0:
            row["flops_model"] = "xla+analytic"
        return row
    except Exception:
        return {}


def build_bench_problem(n: int, rng=None):
    """One source of truth for the engine-benchmark problem: random
    formation + gains + airborne state at the standard scale knobs.
    Used by `bench_all`'s control/flooded rows AND
    `benchmarks/flood_sweep.py`'s re-measurement, so the sweep's rows
    stay apples-to-apples with the committed scale artifacts. Returns
    (formation, sparams, state, k_ca, B) — k_ca the avoidance pruning,
    B the flood block, both part of the metric names (`_k{k}_b{B}`).
    Draw order matters: callers sharing an rng rely on pts, adjacency,
    gains, state being sampled in this order."""
    import jax.numpy as jnp

    from aclswarm_tpu import sim
    from aclswarm_tpu.core.types import SafetyParams, make_formation

    rng = np.random.default_rng(0) if rng is None else rng
    pts = rng.normal(size=(n, 3)).astype(np.float32) * 20
    adj = (np.ones((n, n)) - np.eye(n)).astype(np.float32)
    gains = (rng.normal(size=(n, n, 3, 3)) * 0.01).astype(np.float32)
    f = make_formation(jnp.asarray(pts), jnp.asarray(adj),
                       jnp.asarray(gains))
    sp = SafetyParams(bounds_min=jnp.asarray([-100.0, -100.0, 0.0]),
                      bounds_max=jnp.asarray([100.0, 100.0, 20.0]))
    st = sim.init_state(
        rng.normal(size=(n, 3)).astype(np.float32) * 20 + [0, 0, 2])
    k_ca = 16 if n > 64 else None
    B = 64 if n > 128 else None
    return f, sp, st, k_ca, B


def sinkhorn_throughput(n: int, K: int, reps: int, n_iters: int = 50,
                        seed: int = 0) -> dict:
    """The headline measurement, shared with the repo-root `bench.py`
    driver contract: sustained Hz over a scanned chain of K distinct
    instances + suboptimality vs the exact host LAP. One source of truth
    for the pinned methodology."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from aclswarm_tpu.assignment import lapjv, sinkhorn
    from aclswarm_tpu.core import geometry

    rng = np.random.default_rng(seed)
    p = jnp.asarray(rng.normal(size=(n, 3)).astype(np.float32) * 20)
    qs = jnp.asarray(rng.normal(size=(K, n, 3)).astype(np.float32) * 20)

    def chain(qs):
        def body(c, q):
            r = sinkhorn.sinkhorn_assign(q, p, n_iters=n_iters)
            return c + r.row_to_col.sum(), None
        return lax.scan(body, jnp.int32(0), qs)[0]

    jchain = jax.jit(chain)
    dt = _median_time(jchain, qs, K, reps)
    spread = dict(_LAST_SPREAD)
    # analytic flop counts for the iteration + rounding stages — needed
    # for BOTH impls: the Pallas bodies are opaque to cost_analysis, and
    # the XLA path's scan/while loop bodies are statically counted ONCE
    # (not x n_iters / x rounds), the same under-report class (over-
    # counts the XLA path by its one statically-counted body, ~2%)
    from aclswarm_tpu.ops import rounding_pallas, sinkhorn_pallas
    pallas_flops = (sinkhorn_pallas.analytic_flops(n, n_iters)
                    + rounding_pallas.analytic_flops(n))
    roofline = _roofline(jchain, qs, dt, K, pallas_flops=pallas_flops)

    f1 = jax.jit(
        lambda q: sinkhorn.sinkhorn_assign(q, p, n_iters=n_iters).row_to_col)
    latency = _median_time(f1, qs[0], 1, reps)
    latency_spread = dict(_LAST_SPREAD)
    _LAST_SPREAD.clear()
    # decompose the single-shot latency (round-4 review Weak #4): time a
    # TRIVIAL jitted dispatch through the same launch+readback path — that
    # is the environment's fixed per-executable floor (tunnel + scheduling
    # + readback, ~100 ms here); the remainder is on-device time, cross-
    # checkable against the chained per-instance figure (which amortizes
    # the floor over K instances)
    triv = jax.jit(lambda q: q.sum())
    floor = _median_time(triv, qs[0], 1, reps)
    _LAST_SPREAD.clear()
    # the floor is a DIFFERENT executable through a tunnel with +-20 ms
    # jitter, so latency - floor is noise-dominated (can even go
    # negative); the robust on-device figure is the chained per-instance
    # time, and the residual is reported as-is for honesty
    decomposition = {
        "launch_floor_ms": round(floor * 1e3, 2),
        "on_device_per_instance_ms": round(dt * 1e3, 3),
        "residual_vs_floor_ms": round((latency - floor) * 1e3, 2),
        "note": "single-shot latency ~= per-dispatch floor (a trivial "
                "kernel through the same tunnel + readback path costs "
                "the same) + on-device compute; on-device is taken from "
                "the chained (floor-amortized) per-instance time — the "
                "residual column shows the direct subtraction, which "
                "carries the tunnel's +-20 ms jitter",
    }
    v = np.asarray(f1(qs[0]))
    cost = np.asarray(geometry.cdist(qs[0], p))
    opt = cost[np.arange(n), lapjv(cost)].sum()
    subopt = float(cost[np.arange(n), v].sum() / opt - 1.0)
    return {"hz": 1.0 / dt, "latency_ms": latency * 1000.0,
            "subopt": subopt, "chain_k": K, "n_iters": n_iters,
            "roofline": roofline, "latency_decomposition": decomposition,
            "hz_spread": ([round(1.0 / spread["max_s"], 1),
                           round(1.0 / spread["min_s"], 1)]
                          if spread else None),
            "chain_spread_s": ([round(spread["min_s"], 6),
                                round(spread["max_s"], 6)]
                               if spread else None),
            "latency_spread_s": ([round(latency_spread["min_s"], 6),
                                  round(latency_spread["max_s"], 6)]
                                 if latency_spread else None)}


def trials_throughput(n: int = 100, B: int = 16, m_serial: int | None = None,
                      seed: int = 1, out: str | None = None) -> list[dict]:
    """Monte-Carlo trial throughput: the serial driver (one trial per
    device launch, per-tick host FSM, full `StepMetrics` transfer) vs the
    batched driver (`harness.trials.run_trial_batch`: B trials per
    launch, on-device supervisor reduction, one host sync per chunk).

    Emits `trials_per_minute_n{n}_b1` and `trials_per_minute_n{n}_b{B}`
    rows plus the speedup — the trial-axis scaling artifact. Both modes
    run the SAME trial set (seeds seed..seed+B-1; `m_serial` overrides
    the serial count when B serial trials are too expensive) through the
    simform{n} Sinkhorn config shape (trials_suite's scale rows) with
    dispatch-aligned chunks (chunk_ticks = assign_every = 120).

    Interpretation note (recorded in the rows): the batch amortizes
    per-launch and per-chunk HOST costs — dispatch floor, metric
    transfer, the per-tick FSM loop. On a host where those dominate (the
    remote-TPU tunnel's measured ~108 ms per-dispatch floor, or any
    accelerator a single n=100 trial underutilizes) B trials ride one
    launch for far less than B x the time; on a saturated single CPU
    core the engine is compute-bound and the ratio approaches the
    compaction win only."""
    import dataclasses as _dc
    import os

    import jax

    from aclswarm_tpu.harness import trials as triallib

    if m_serial is None:
        m_serial = B
    base = dict(formation=f"simform{n}", assignment="sinkhorn",
                colavoid_neighbors=16 if n > 64 else None,
                chunk_ticks=120,
                sim_l=40.0, sim_w=40.0, sim_h=3.0, sim_min_dist=3.0,
                init_area_w=40.0, init_area_h=40.0, init_radius=1.0,
                room_x=100.0, room_y=100.0, room_z=30.0,
                seed=seed, verbose=False)
    rows = []
    host = {"device": jax.devices()[0].platform,
            "n_devices": len(jax.devices()),
            "cpu_count": os.cpu_count()}

    def emit(metric, value, unit, **extra):
        row = {"metric": metric, "value": round(float(value), 3),
               "unit": unit, **host}
        row.update(extra)
        rows.append(row)
        print(json.dumps(row), flush=True)
        if out:
            path = Path(out)
            path.parent.mkdir(parents=True, exist_ok=True)
            with open(path, "a") as fh:
                fh.write(json.dumps(row) + "\n")

    # serial reference driver (b1): warm one trial for compile, then time
    cfg = triallib.TrialConfig(trials=m_serial, **base)
    triallib.run_trial(cfg, 0)
    t0 = time.time()
    fsm_s = [triallib.run_trial(cfg, t) for t in range(m_serial)]
    wall_s = time.time() - t0
    per_trial_s = wall_s / m_serial
    completed_s = sum(f.completed for f in fsm_s)
    # per-chunk host transfer of the serial driver: the five StepMetrics
    # arrays it converts (q f64/f32 + distcmd_norm + ca + 3 scalars/tick)
    itemsize = 8 if jax.config.jax_enable_x64 else 4
    chunk = cfg.chunk_ticks
    serial_bytes_per_chunk = chunk * (n * 3 * itemsize + n * itemsize
                                      + n + 3)
    emit(f"trials_per_minute_n{n}_b1", 60.0 / per_trial_s, "trials/min",
         trials=m_serial, completed=completed_s,
         wall_s_per_trial=round(per_trial_s, 2),
         host_bytes_per_chunk_per_trial=serial_bytes_per_chunk)

    # batched driver: the same B trials in one wave. One full warm pass
    # first: the serial row was compiled by its warm trial, and the
    # batched program's (B, chunk, n)-shaped executables (including the
    # power-of-two compaction buckets) must get the same treatment or
    # their one-time compiles pollute the throughput number.
    cfgb = _dc.replace(cfg, trials=B, batch=B)
    triallib.run_trial_batch(cfgb, list(range(B)))
    t0 = time.time()
    fsm_b = triallib.run_trial_batch(cfgb, list(range(B)))
    wall_b = time.time() - t0
    per_trial_b = wall_b / B
    completed_b = sum(f.completed for f in fsm_b)
    # batched per-chunk sync per trial: 6 bool tick-vectors + (n,) dists
    batched_bytes_per_chunk = chunk * 6 + n * itemsize
    emit(f"trials_per_minute_n{n}_b{B}", 60.0 / per_trial_b, "trials/min",
         trials=B, completed=completed_b, batch=B,
         wall_s_per_trial=round(per_trial_b, 2),
         host_bytes_per_chunk_per_trial=batched_bytes_per_chunk)
    emit(f"trials_batch_speedup_n{n}_b{B}", per_trial_s / per_trial_b,
         "ratio", transfer_reduction=round(
             serial_bytes_per_chunk / batched_bytes_per_chunk, 1),
         note=(
             "speedup = host-overhead amortization x compaction; on a "
             "launch-floor-dominated host (remote-TPU tunnel, ~108 ms "
             "per dispatch) the b1 driver pays the floor every chunk "
             "per trial while b16 pays it once per chunk for 16 trials"))
    return rows


def _committed_metrics(out: str | None) -> set:
    """Metric names already appended to ``out`` — the mid-grid resume
    set (docs/RESILIENCE.md): rows append incrementally, so a killed
    suite resumed with --resume re-measures only the missing rows."""
    done = set()
    if out and Path(out).exists():
        for line in Path(out).read_text().splitlines():
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(row, dict) and "metric" in row:
                done.add(row["metric"])
    return done


def bench_all(n: int, quick: bool = False, sharded: bool = False,
              out: str | None = None, gains1000: bool = False,
              resume: bool = False):
    import jax
    import jax.numpy as jnp
    from jax import lax

    from aclswarm_tpu import sim
    from aclswarm_tpu.assignment import lapjv, sinkhorn
    from aclswarm_tpu.core import geometry
    from aclswarm_tpu.core.types import (ControlGains, SafetyParams,
                                         make_formation)

    rng = np.random.default_rng(0)
    results = []
    reps = 2 if quick else 5
    done_metrics = _committed_metrics(out) if resume else set()
    if done_metrics:
        print(f"# --resume: {len(done_metrics)} metrics already in {out}; "
              "skipping those measurements", flush=True)

    def todo(*metrics) -> bool:
        """False when every named metric is already committed (resume)."""
        missing = [m for m in metrics if m not in done_metrics]
        if not missing:
            print(f"# skip (resumed): {', '.join(metrics)}", flush=True)
        return bool(missing)

    def emit(metric, value, unit, baseline=None, **extra):
        if metric in done_metrics:
            _LAST_SPREAD.clear()
            return                 # resumed: row already committed
        row = {"metric": metric, "value": round(float(value), 3),
               "unit": unit,
               "device": jax.devices()[0].platform,
               "n_devices": len(jax.devices())}
        if baseline is not None:
            row["vs_baseline"] = round(float(value) / baseline, 2)
        if _LAST_SPREAD:
            # jitter column: the rep spread behind the median (same
            # per-divisor), so regressions show beyond the one number;
            # consumed once — derived rows (subopt, match) carry none
            row["spread_s"] = [round(_LAST_SPREAD["min_s"], 6),
                               round(_LAST_SPREAD["max_s"], 6)]
            _LAST_SPREAD.clear()
        row.update(extra)
        results.append(row)
        print(json.dumps(row), flush=True)
        if out:
            # append immediately: a crashed device (or tunnel watchdog)
            # mid-suite must not discard the rows already measured
            path = Path(out)
            path.parent.mkdir(parents=True, exist_ok=True)
            with open(path, "a") as fh:
                fh.write(json.dumps(row) + "\n")

    # --- full 100 Hz control tick at scale (chained rollout) ---
    # NOTE for --resume: every rng draw below stays UNCONDITIONAL (array
    # builds are cheap); only jit + timing are skipped — so a resumed
    # run measures exactly the instances a fresh run would have
    f, sp, st, k_ca, B = build_bench_problem(n, rng)
    # the pruning parameter is part of the metric name: with k-neighbor
    # pruning the avoidance kernel is approximate when > k vehicles are
    # inside d_avoid_thresh (see control.collision_avoidance)
    ca_tag = f"_k{k_ca}" if k_ca is not None else ""
    btag = f"_b{B}" if B else ""
    if todo(f"control_tick_n{n}{ca_tag}_hz"):
        cfg = sim.SimConfig(assignment="none", colavoid_neighbors=k_ca)
        ticks = 50 if quick else 200
        roll = jax.jit(lambda s: sim.rollout(s, f, ControlGains(), sp,
                                             cfg, ticks)[0])
        dt = _median_time(roll, st, ticks, reps)
        emit(f"control_tick_n{n}{ca_tag}_hz", 1.0 / dt, "Hz",
             baseline=100.0, **_roofline(roll, st, dt, ticks))

    # --- streaming re-assignment (north star config 5): the full engine
    # tick with a fresh Sinkhorn assignment EVERY tick — the gridlock-
    # recovery mode where the swarm continuously re-auctions ---
    if todo(f"streaming_reassign_n{n}{ca_tag}_hz"):
        stream_cfg = sim.SimConfig(assignment="sinkhorn", assign_every=1,
                                   dynamics="firstorder",
                                   colavoid_neighbors=k_ca)
        ticks_s = 20 if quick else 100
        stream = jax.jit(lambda s: sim.rollout(
            s, f, ControlGains(), sp, stream_cfg, ticks_s)[0])
        dt = _median_time(stream, st, ticks_s, reps)
        emit(f"streaming_reassign_n{n}{ca_tag}_hz", 1.0 / dt, "Hz",
             baseline=100.0)

    # --- faithful modes at scale (round-2 weak #4): the real information
    # model (flooded localization, blocked merge) and the decentralized
    # CBAA auction (blocked consensus) at the SAME n as the north star.
    # Block sizes keep peak memory O(n^2 B) — the dense (n, n, n) forms
    # need 4 GB at n=1000 and cannot run on one chip. B comes from
    # build_bench_problem (shared with flood_sweep's re-measurements). ---
    flood_cfg = sim.SimConfig(assignment="none", localization="flooded",
                              flood_block=B, colavoid_neighbors=k_ca)
    st_loc = sim.init_state(
        rng.normal(size=(n, 3)).astype(np.float32) * 20 + [0, 0, 2],
        localization=True)
    ticks_f = 20 if quick else 100
    # analytic flops for the flood merge — needed for BOTH impls:
    # the Pallas body is opaque to cost_analysis, and the blocked-XLA
    # path's lax.map body is statically counted once (not x n/B trips),
    # so both under-report the same O(n^2 w) reduction (measured at
    # n=2000: XLA reported 3.3e8 where the reduction does ~8e9; the
    # analytic figure over-counts the XLA path by its one statically-
    # counted block, ~3%). Per TICK: the bulk flood merges every
    # `flood_every`=2 ticks; the roundtick metric merges every tick;
    # phased2 does a half-width stripe every tick.
    from aclswarm_tpu.ops import flood_pallas as fpal

    def _merge_flops(w=None):
        return float(fpal.analytic_flops(n, w))

    if todo(f"flooded_tick_n{n}{ca_tag}{btag}_hz"):
        froll = jax.jit(lambda s: sim.rollout(s, f, ControlGains(), sp,
                                              flood_cfg, ticks_f)[0])
        dt = _median_time(froll, st_loc, ticks_f, reps)
        emit(f"flooded_tick_n{n}{ca_tag}{btag}_hz", 1.0 / dt, "Hz",
             baseline=100.0,
             **_roofline(froll, st_loc, dt, ticks_f,
                         pallas_flops=_merge_flops() / 2))

    # the WORST tick of the bulk flood (every 2nd tick does the whole
    # O(n^3) merge; the average above hides the spike): flood_every=1
    # makes every tick a flood-round tick, so the mean IS the spike
    if todo(f"flooded_roundtick_n{n}{ca_tag}{btag}_hz"):
        spike_cfg = sim.SimConfig(assignment="none",
                                  localization="flooded",
                                  flood_block=B, colavoid_neighbors=k_ca,
                                  flood_every=1)
        sroll = jax.jit(lambda s: sim.rollout(s, f, ControlGains(), sp,
                                              spike_cfg, ticks_f)[0])
        dt = _median_time(sroll, st_loc, ticks_f, reps)
        emit(f"flooded_roundtick_n{n}{ca_tag}{btag}_hz", 1.0 / dt, "Hz",
             baseline=100.0, **_roofline(sroll, st_loc, dt, ticks_f,
                                         pallas_flops=_merge_flops()))

    # phased flood (flood_phases=2): the merge's target axis spreads over
    # the 50 Hz window, so EVERY tick carries half a merge and none
    # spikes — per-target cadence unchanged (`localization.tick_phased`)
    if todo(f"flooded_tick_n{n}{ca_tag}{btag}_phased2_hz"):
        ph_cfg = sim.SimConfig(assignment="none", localization="flooded",
                               flood_block=B, colavoid_neighbors=k_ca,
                               flood_phases=2)
        proll = jax.jit(lambda s: sim.rollout(s, f, ControlGains(), sp,
                                              ph_cfg, ticks_f)[0])
        dt = _median_time(proll, st_loc, ticks_f, reps)
        emit(f"flooded_tick_n{n}{ca_tag}{btag}_phased2_hz", 1.0 / dt,
             "Hz", baseline=100.0,
             **_roofline(proll, st_loc, dt, ticks_f,
                         pallas_flops=_merge_flops(w=(n + 1) // 2)))

    from aclswarm_tpu.assignment import cbaa as cbaalib
    from aclswarm_tpu.core import perm as permutil
    v2f0 = permutil.identity(n)
    # Faithful consensus, two numbers: (1) the deployment form with the
    # bit-identical fixed-point early exit (typically tens of rounds) —
    # cheap, always measured; (2) the reference's fixed 2n-round budget
    # (`auctioneer.cpp:50-51`) for latency parity — minutes-long on a CPU
    # mesh at scale, so --quick skips *it* at n>512 (the committed TPU
    # artifact carries the honest number; chain kept at 1 there: a K=8
    # full-budget chain crashed the TPU worker through the tunnel
    # watchdog).
    Kc = 2 if quick else 8
    qs_c = jnp.asarray(rng.normal(size=(Kc, n, 3)).astype(np.float32) * 20)

    def cchain(qs_c):
        def body(c, q):
            r = cbaalib.cbaa_from_state(q, f.points, f.adjmat, v2f0,
                                        task_block=B)
            return c + r.v2f.sum() + r.rounds, None
        return lax.scan(body, jnp.int32(0), qs_c)[0]

    if todo(f"cbaa_faithful_earlyexit_n{n}{btag}_hz"):
        rr = jax.jit(lambda q: cbaalib.cbaa_from_state(
            q, f.points, f.adjmat, v2f0, task_block=B))(qs_c[0])
        jc = jax.jit(cchain)
        dt = _median_time(jc, qs_c, Kc, max(2, reps - 3))
        # keyed `_earlyexit` since round 4: the pre-round-3
        # `cbaa_faithful_n*` key measured the fixed 2n-round budget (now
        # `cbaa_fullbudget_n*`); distinct keys keep cross-commit
        # artifact comparisons like-for-like
        emit(f"cbaa_faithful_earlyexit_n{n}{btag}_hz", 1.0 / dt, "Hz",
             chain_k=Kc, s_per_auction=round(dt, 4),
             rounds=int(rr.rounds), budget=2 * n, valid=bool(rr.valid),
             **_roofline(jc, qs_c, dt, Kc))

    # the fixed 2n-round budget is a single ~n^2-round dispatch: beyond
    # n~1000 (9.5 s) it exceeds this environment's device watchdog — a
    # 2x2000-round dispatch (~40 s) CRASHED the TPU worker through the
    # tunnel (measured, round 4). Latency parity is pinned at n<=1000;
    # the early-exit row above is the deployment number at every n.
    if n <= 1024 and not (quick and n > 512) \
            and todo(f"cbaa_fullbudget_n{n}{btag}_hz"):
        Kb = 1 if n > 512 else Kc

        def cchain_budget(qs_c):
            def body(c, q):
                r = cbaalib.cbaa_from_state(q, f.points, f.adjmat, v2f0,
                                            task_block=B, early_exit=False)
                return c + r.v2f.sum(), None
            return lax.scan(body, jnp.int32(0), qs_c[:Kb])[0]

        dt = _median_time(jax.jit(cchain_budget), qs_c, Kb, 2)
        emit(f"cbaa_fullbudget_n{n}{btag}_hz", 1.0 / dt, "Hz", chain_k=Kb,
             s_per_auction=round(dt, 3))

    # --- sinkhorn assignment at scale (chained over distinct instances;
    # K = 400 bounds the ~108 ms fixed launch floor to ~0.27 ms/instance) ---
    K = 10 if quick else 400
    n_iters = 50
    if todo(f"sinkhorn_assign_n{n}_hz",
            f"sinkhorn_assign_n{n}_latency_ms",
            f"sinkhorn_assign_n{n}_subopt"):
        sk = sinkhorn_throughput(n, K, reps, n_iters=n_iters)
        # spreads attached explicitly: sinkhorn_throughput runs TWO
        # timings (chained + single-shot), so the implicit last-spread
        # would tag the throughput row with the latency run's jitter
        emit(f"sinkhorn_assign_n{n}_hz", sk["hz"], "Hz", baseline=100.0,
             chain_k=K, spread_s=sk["chain_spread_s"],
             **(sk["roofline"] or {}))
        # single-shot latency (includes this environment's fixed
        # per-launch tunnel overhead — see module docstring; honest but
        # pessimistic), with the floor/on-device decomposition attached
        emit(f"sinkhorn_assign_n{n}_latency_ms", sk["latency_ms"], "ms",
             spread_s=sk["latency_spread_s"],
             decomposition=sk["latency_decomposition"])
        emit(f"sinkhorn_assign_n{n}_subopt", sk["subopt"], "ratio")

    # --- sharded assignment over the device mesh (agent-axis GSPMD) ---
    if sharded and len(jax.devices()) > 1:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from aclswarm_tpu.parallel import mesh as meshlib
        # the mesh helper trims to the largest device count dividing n
        mesh = meshlib.make_mesh(n_agents=n)
        ndev = len(mesh.devices.ravel())
        qs = jnp.asarray(rng.normal(size=(K, n, 3)).astype(np.float32) * 20)
        p = f.points          # the shared bench problem's formation
        row_t = NamedSharding(mesh, P(None, meshlib.AGENT_AXIS))
        rep = meshlib.replicated(mesh)

        def chain(qs):
            def body(c, q):
                r = sinkhorn.sinkhorn_assign(q, p, n_iters=n_iters)
                return c + r.row_to_col.sum(), None
            return lax.scan(body, jnp.int32(0), qs)[0]

        if todo(f"sinkhorn_assign_n{n}_sharded{ndev}_hz"):
            fsh = jax.jit(chain, in_shardings=(row_t,),
                          out_shardings=rep)
            dt = _median_time(fsh, jax.device_put(qs, row_t), K, reps)
            emit(f"sinkhorn_assign_n{n}_sharded{ndev}_hz", 1.0 / dt,
                 "Hz", baseline=100.0, chain_k=K)

        # staged shardings (docs/SCALING.md): iterations sharded, the
        # sequential rounding/repair loops replicated — one gather instead
        # of per-round collectives
        row_q = meshlib.row_sharding(mesh)

        def chain_staged(qs):
            def body(c, q):
                r = sinkhorn.sinkhorn_assign(
                    q, p, n_iters=n_iters, stage_shardings=(row_q, rep))
                return c + r.row_to_col.sum(), None
            return lax.scan(body, jnp.int32(0), qs)[0]

        if todo(f"sinkhorn_assign_n{n}_sharded{ndev}_staged_hz"):
            fst = jax.jit(chain_staged, in_shardings=(row_t,),
                          out_shardings=rep)
            dt = _median_time(fst, jax.device_put(qs, row_t), K, reps)
            emit(f"sinkhorn_assign_n{n}_sharded{ndev}_staged_hz",
                 1.0 / dt, "Hz", baseline=100.0, chain_k=K)
        if todo(f"sinkhorn_assign_n{n}_sharded{ndev}_match"):
            # correctness: sharded == single-device rounding decisions
            v_ref = np.asarray(jax.jit(
                lambda q: sinkhorn.sinkhorn_assign(
                    q, p, n_iters=n_iters).row_to_col)(qs[0]))
            v_sh = np.asarray(jax.jit(
                lambda q: sinkhorn.sinkhorn_assign(
                    q, p, n_iters=n_iters).row_to_col,
                in_shardings=(meshlib.row_sharding(mesh),))(
                    jax.device_put(qs[0], meshlib.row_sharding(mesh))))
            emit(f"sinkhorn_assign_n{n}_sharded{ndev}_match", float(
                np.mean(v_sh == v_ref)), "ratio")

    # --- gain design (ADMM), simform100-shape sparse graph ---
    n_g = min(n, 100)
    from aclswarm_tpu import gains as gl
    from aclswarm_tpu.harness import formgen

    G = 4 if quick else 40
    ptss = jnp.asarray(
        rng.normal(size=(G, n_g, 3)).astype(np.float32) * 10)
    for tag, adj_g in (
            ("", np.ones((n_g, n_g)) - np.eye(n_g)),
            ("_sparse", formgen.random_adjmat(
                np.random.default_rng(7), n_g, fc=False))):
        if not todo(f"admm_gain_design_n{n_g}{tag}_ms"):
            continue

        def gchain(ptss, adj_g=adj_g):
            def body(c, pp):
                return c + gl.solve_gains(
                    pp, adj_g, max_nonedges=n_g - 4).sum(), None
            return lax.scan(body, jnp.float32(0), ptss)[0]

        jg = jax.jit(gchain)
        dt = _median_time(jg, ptss, G, reps)
        emit(f"admm_gain_design_n{n_g}{tag}_ms", dt * 1000, "ms",
             chain_k=G, **_roofline(jg, ptss, dt, G))

    # --- gain design at n=1000 (north star config 4, the honest number):
    # a (3992, 3992)-matrix ADMM solve; runs per formation *dispatch*
    # (1.2 s auto-auction cadence), not per control tick, so seconds-scale
    # is usable — but nowhere near 100 Hz, reported as-is. Off by default
    # (~2 min compile + ~4 s/solve); enable with --gains1000. ---
    if gains1000 and todo(f"admm_gain_design_n{n}_s"):
        pts1k = jnp.asarray(rng.normal(size=(n, 3)).astype(np.float32) * 30)
        adj1k = np.ones((n, n)) - np.eye(n)
        g1k = jax.jit(lambda p: gl.solve_gains(
            p, adj1k, max_nonedges=n - 4).sum())
        dt = _median_time(g1k, pts1k, 1, max(2, reps - 2))
        emit(f"admm_gain_design_n{n}_s", dt, "s",
             **_roofline(g1k, pts1k, dt, 1))

    if out:
        print(f"# wrote {len(results)} rows to {out} (incrementally)")
    return results


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=1000)
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--sharded", action="store_true")
    ap.add_argument("--out", default=None)
    ap.add_argument("--gains1000", action="store_true",
                    help="include the n=1000 gain-design solve (slow compile)")
    ap.add_argument("--trials-batch", action="store_true",
                    help="measure Monte-Carlo trial throughput, serial "
                         "vs batched (trials_per_minute_* rows) instead "
                         "of the kernel suite")
    ap.add_argument("--batch", type=int, default=16,
                    help="(with --trials-batch) trials per launch")
    ap.add_argument("--trials-n", type=int, default=100,
                    help="(with --trials-batch) agents per trial")
    ap.add_argument("--resume", action="store_true",
                    help="(with --out) skip metrics the results file "
                    "already carries — mid-grid resume of a killed "
                    "suite (docs/RESILIENCE.md); rng draws still run "
                    "so the remaining instances match a fresh run")
    args = ap.parse_args()
    # the axon TPU plugin ignores JAX_PLATFORMS=cpu; apply it through
    # jax.config so virtual-mesh runs actually land on CPU
    import os
    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
    if args.trials_batch:
        trials_throughput(args.trials_n, B=args.batch, out=args.out)
        return
    bench_all(args.n, args.quick, args.sharded, args.out,
              gains1000=args.gains1000, resume=args.resume)


if __name__ == "__main__":
    main()
