"""Scale benchmarks: the north-star numbers (BASELINE.md) on real hardware.

Prints one JSON line per metric. Methodology: work is chained inside a
single jit (scan over distinct inputs or dependent rollout steps) so numbers
are true per-op latencies, not pipelined-dispatch artifacts (the device
runtime dedupes identical repeated dispatches).

Run: python benchmarks/scale.py [--n 1000] [--quick]
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def bench_all(n: int, quick: bool = False):
    import jax
    import jax.numpy as jnp
    from jax import lax

    from aclswarm_tpu import sim
    from aclswarm_tpu.assignment import lapjv, sinkhorn
    from aclswarm_tpu.core import geometry
    from aclswarm_tpu.core.types import (ControlGains, SafetyParams,
                                         make_formation)

    rng = np.random.default_rng(0)
    results = []

    def emit(metric, value, unit, baseline=None):
        row = {"metric": metric, "value": round(float(value), 3),
               "unit": unit}
        if baseline is not None:
            row["vs_baseline"] = round(float(value) / baseline, 2)
        results.append(row)
        print(json.dumps(row))

    # --- full 100 Hz control tick at scale (chained rollout) ---
    pts = rng.normal(size=(n, 3)).astype(np.float32) * 20
    adj = (np.ones((n, n)) - np.eye(n)).astype(np.float32)
    gains = (rng.normal(size=(n, n, 3, 3)) * 0.01).astype(np.float32)
    f = make_formation(jnp.asarray(pts), jnp.asarray(adj),
                       jnp.asarray(gains))
    sp = SafetyParams(bounds_min=jnp.asarray([-100.0, -100.0, 0.0]),
                      bounds_max=jnp.asarray([100.0, 100.0, 20.0]))
    st = sim.init_state(
        rng.normal(size=(n, 3)).astype(np.float32) * 20 + [0, 0, 2])
    k_ca = 16 if n > 64 else None
    cfg = sim.SimConfig(assignment="none", colavoid_neighbors=k_ca)
    ticks = 50 if quick else 200
    roll = jax.jit(lambda s: sim.rollout(s, f, ControlGains(), sp, cfg,
                                         ticks)[0])
    jax.block_until_ready(roll(st))
    t0 = time.perf_counter()
    jax.block_until_ready(roll(st))
    dt = (time.perf_counter() - t0) / ticks
    # the pruning parameter is part of the metric name: with k-neighbor
    # pruning the avoidance kernel is approximate when > k vehicles are
    # inside d_avoid_thresh (see control.collision_avoidance)
    ca_tag = f"_k{k_ca}" if k_ca is not None else ""
    emit(f"control_tick_n{n}{ca_tag}_hz", 1.0 / dt, "Hz", baseline=100.0)

    # --- sinkhorn assignment at scale (chained over distinct instances) ---
    K = 5 if quick else 20
    qs = jnp.asarray(rng.normal(size=(K, n, 3)).astype(np.float32) * 20)
    p = jnp.asarray(pts)

    def chain(qs):
        def body(c, q):
            r = sinkhorn.sinkhorn_assign(q, p, n_iters=50)
            return c + r.row_to_col.sum(), None
        return lax.scan(body, jnp.int32(0), qs)[0]

    fj = jax.jit(chain)
    jax.block_until_ready(fj(qs))
    t0 = time.perf_counter()
    jax.block_until_ready(fj(qs))
    dt = (time.perf_counter() - t0) / K
    emit(f"sinkhorn_assign_n{n}_hz", 1.0 / dt, "Hz", baseline=100.0)

    # quality vs exact LAP
    v = np.asarray(jax.jit(
        lambda q: sinkhorn.sinkhorn_assign(q, p, n_iters=50).row_to_col)(
            qs[0]))
    cost = np.asarray(geometry.cdist(qs[0], p))
    opt = cost[np.arange(n), lapjv(cost)].sum()
    emit(f"sinkhorn_assign_n{n}_subopt", cost[np.arange(n), v].sum() / opt - 1,
         "ratio")

    # --- gain design (ADMM) ---
    n_g = min(n, 100)
    adj_g = np.ones((n_g, n_g)) - np.eye(n_g)
    from aclswarm_tpu import gains as gl

    # chained over distinct point sets
    ptss = jnp.asarray(
        rng.normal(size=(3, n_g, 3)).astype(np.float32) * 10)

    def gchain(ptss):
        def body(c, pp):
            return c + gl.solve_gains(pp, adj_g).sum(), None
        return lax.scan(body, jnp.float32(0), ptss)[0]

    gj = jax.jit(gchain)
    jax.block_until_ready(gj(ptss))
    t0 = time.perf_counter()
    jax.block_until_ready(gj(ptss))
    dt = (time.perf_counter() - t0) / 3
    emit(f"admm_gain_design_n{n_g}_ms", dt * 1000, "ms")

    return results


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=1000)
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()
    bench_all(args.n, args.quick)


if __name__ == "__main__":
    main()
