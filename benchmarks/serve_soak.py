"""Multi-client chaos soak for swarmserve — the serving-axis flagship
benchmark (docs/SERVICE.md; docs/SCALING.md names independent problem
instances as "the axis that maps to serving traffic").

Three concurrent tenants submit a mixed stream of shape-heterogeneous
rollout / assignment / gain-design requests — several carrying their own
`FaultSchedule` scripts, one with an already-expired deadline, one
tenant deliberately flooding past its admission cap — while a scripted
`CrashPlan` SIGKILLs the service worker process MID-BATCH. A second
service process recovers the journal and drains. The parent then audits
the promise ledger:

- **zero silent losses**: every accepted request has a terminal
  done-frame (result or structured error);
- **bit-identical resume**: every completed rollout's digest matches an
  uninterrupted reference service run;
- **latency SLO evidence**: p50/p95/p99 over accepted->terminal wall
  latency, committed to `benchmarks/results/serve_soak.json`
  (schema-guarded by `benchmarks/check_results.py`).

Run:

    JAX_PLATFORMS=cpu python benchmarks/serve_soak.py [--quick] \
        [--out benchmarks/results/serve_soak.json]

Exit 1 on any broken promise (a loss, a non-terminal request, a resume
digest mismatch) — the artifact is only committed from a green run.
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

RESULTS = Path(__file__).resolve().parent / "results"
KILL_ROUND = 6          # mid-batch: several chunks in, none finished all

TENANTS = ("alpha", "beta", "gamma")


def request_mix(quick: bool) -> list[dict]:
    """The soak's request stream: shape-heterogeneous (n=5 and n=8
    buckets), fault-scripted, deadline-edged. Deterministic — phase B
    recovery and the parent's reference runs must agree on it."""
    ticks = 60 if quick else 120
    mix = [
        # tenant alpha: plain + faulted n=5 rollouts
        {"kind": "rollout", "tenant": "alpha", "request_id": "a-roll0",
         "params": {"n": 5, "ticks": ticks, "chunk_ticks": 20,
                    "seed": 10}},
        {"kind": "rollout", "tenant": "alpha", "request_id": "a-roll1",
         "params": {"n": 5, "ticks": ticks, "chunk_ticks": 20, "seed": 11,
                    "faults": {"dropout_frac": 0.4, "drop_tick": 15,
                               "rejoin_tick": 55}}},
        # tenant beta: the second shape bucket (n=8) + lossy links
        {"kind": "rollout", "tenant": "beta", "request_id": "b-roll0",
         "params": {"n": 8, "ticks": ticks, "chunk_ticks": 20, "seed": 20,
                    "faults": {"link_loss": 0.2}}},
        {"kind": "rollout", "tenant": "beta", "request_id": "b-roll1",
         "params": {"n": 8, "ticks": ticks, "chunk_ticks": 20,
                    "seed": 21}},
        # tenant gamma: single-shot kinds + the dead-on-arrival deadline
        {"kind": "assign", "tenant": "gamma", "request_id": "g-assign",
         "params": {"n": 16, "seed": 30}},
        {"kind": "gains", "tenant": "gamma", "request_id": "g-gains",
         "params": {"n": 5, "seed": 31}},
        {"kind": "rollout", "tenant": "gamma", "request_id": "g-late",
         "deadline_s": 0.0,
         "params": {"n": 5, "ticks": ticks, "chunk_ticks": 20,
                    "seed": 32}},
    ]
    if not quick:
        mix += [
            {"kind": "rollout", "tenant": "alpha",
             "request_id": "a-roll2",
             "params": {"n": 8, "ticks": ticks, "chunk_ticks": 20,
                        "seed": 12, "faults": {"dropout_frac": 0.25,
                                               "drop_tick": 40}}},
            {"kind": "assign", "tenant": "beta", "request_id": "b-assign",
             "params": {"n": 16, "seed": 22, "solver": "lap"}},
        ]
    return mix


def flood_burst(quick: bool) -> list[dict]:
    """Tenant alpha's oversubscription burst: more queued work than its
    admission cap allows — the rejected remainder is the backpressure
    evidence (client-side, never journaled)."""
    n_flood = 4 if quick else 8
    return [
        {"kind": "rollout", "tenant": "alpha",
         "request_id": f"a-flood{i}",
         "params": {"n": 5, "ticks": 40, "chunk_ticks": 20,
                    "seed": 100 + i}}
        for i in range(n_flood)
    ]


def _service(journal: str):
    from aclswarm_tpu.serve import ServiceConfig, SwarmService

    # tight caps + 1-chunk quantum + 2 batch slots: preemption and
    # rejection both OCCUR (a soak that never exercises its guarantees
    # proves nothing)
    return SwarmService(ServiceConfig(
        max_batch=2, quantum_chunks=1, max_queue_per_tenant=4,
        max_queue_total=16, journal_dir=journal))


def child(journal: str, quick: bool) -> int:
    """One service lifetime: submit the mix (+ flood), report the
    client-side view, wait for every ticket. Run 1 is SIGKILLed by the
    env-armed CrashPlan mid-wait; run 2 recovers the same journal,
    resubmits idempotently (duplicate ids attach, terminal ids resolve
    from the journal) and drains to idle."""
    from aclswarm_tpu.serve import RejectedError

    svc = _service(journal)
    tickets, rejected = [], []
    for spec in request_mix(quick) + flood_burst(quick):
        try:
            tickets.append(svc.submit(
                spec["kind"], spec["params"], tenant=spec["tenant"],
                request_id=spec["request_id"],
                deadline_s=spec.get("deadline_s")))
        except RejectedError as e:
            rejected.append({"request_id": spec["request_id"],
                             "retry_after_s": round(e.retry_after_s, 3)})
    print("CLIENT " + json.dumps({
        "submitted": len(tickets), "rejected": rejected}), flush=True)
    for t in tickets:
        t.result(timeout=600)
    svc.close()
    # swarmscope snapshot (docs/OBSERVABILITY.md): occupancy, queue
    # depth, per-tenant latency — printed evidence next to the ledger
    # (the committed soak artifact keeps its exact-key-set schema)
    print("TELEMETRY " + json.dumps(svc.serve_stats().to_row(),
                                    sort_keys=True), flush=True)
    print("CHILD_DONE", flush=True)
    return 0


def _reference_digests(specs: list[dict]) -> dict[str, int]:
    """Uninterrupted solo-service run of every rollout spec — the
    bit-parity oracle for the crashed+preempted+resumed soak results."""
    from aclswarm_tpu.serve import ServiceConfig, SwarmService

    ref = SwarmService(ServiceConfig(max_batch=4))
    # submit everything first: same-bucket specs share device batches
    # (digests are batch-invariant by the engine's row-independence
    # guarantee), so the oracle costs ~one residency per bucket, not
    # one per spec
    tickets = [(s["request_id"],
                ref.submit(s["kind"], s["params"], tenant=s["tenant"]))
               for s in specs]
    out = {}
    for rid, t in tickets:
        res = t.result(600)
        assert res.ok, f"reference run failed for {rid}"
        out[rid] = int(res.value["digest"])
    ref.close()
    return out


def run_soak(out: str | None, quick: bool) -> int:
    from aclswarm_tpu.resilience.crash import ENV_VAR
    from aclswarm_tpu.serve.service import _read_frame

    t_start = time.time()
    problems: list[str] = []
    with tempfile.TemporaryDirectory(prefix="aclswarm_soak_") as d:
        # phase A: clients + worker, SIGKILL mid-batch
        env = dict(os.environ, **{ENV_VAR: f"serve:{KILL_ROUND}:kill"})
        argv = [sys.executable, __file__, "--child", "--dir", d]
        if quick:
            argv.append("--quick")
        rA = subprocess.run(argv, env=env, capture_output=True, text=True,
                            timeout=900)
        if rA.returncode != -signal.SIGKILL:
            print(f"FAIL: phase-A child exited {rA.returncode}, expected "
                  f"SIGKILL\n{rA.stdout}\n{rA.stderr}")
            return 1
        client = json.loads(next(
            ln for ln in rA.stdout.splitlines()
            if ln.startswith("CLIENT ")).split(" ", 1)[1])
        print(f"phase A: SIGKILL at serve round {KILL_ROUND}; "
              f"{client['submitted']} accepted, "
              f"{len(client['rejected'])} rejected with retry-after")

        # phase B: recovery on the same journal, drain to idle
        envB = dict(os.environ)
        envB.pop(ENV_VAR, None)
        rB = subprocess.run(argv, env=envB, capture_output=True,
                            text=True, timeout=900)
        if rB.returncode != 0 or "CHILD_DONE" not in rB.stdout:
            print(f"FAIL: phase-B child exited {rB.returncode}\n"
                  f"{rB.stdout}\n{rB.stderr}")
            return 1
        print("phase B: journal recovered, drained to all-tenants-idle")
        tel_line = next((ln for ln in rB.stdout.splitlines()
                         if ln.startswith("TELEMETRY ")), None)
        if tel_line:
            tel = json.loads(tel_line.split(" ", 1)[1])
            print("phase B telemetry: occupancy_mean="
                  f"{tel['occupancy_mean']:.3f} queue_depth_p95="
                  f"{tel['queue_depth_p95']:.1f} rounds={tel['rounds']}")

        # audit the promise ledger
        ledger: dict[str, dict] = {}
        values: dict[str, dict] = {}
        for reqf in Path(d).glob("req_*.req"):
            rid = reqf.name[len("req_"):-len(".req")]
            donef = reqf.with_suffix(".done")
            if not donef.exists():
                problems.append(f"SILENT LOSS: {rid} accepted, never "
                                "terminal")
                continue
            payload, man = _read_frame(donef)
            ledger[rid] = man
            values[rid] = payload
        accepted = len(list(Path(d).glob("req_*.req")))
        statuses = {k: v["status"] for k, v in ledger.items()}
        completed = sum(1 for s in statuses.values() if s == "completed")
        timed_out = sum(1 for s in statuses.values() if s == "timed_out")
        failed = sum(1 for s in statuses.values() if s == "failed")
        silent = accepted - (completed + timed_out + failed)
        preempted = sum(int(v.get("preemptions", 0))
                        for v in ledger.values())
        resumed = sum(1 for v in ledger.values() if v.get("resumed"))
        lat = sorted(float(v["latency_s"]) for v in ledger.values())
        if statuses.get("g-late") != "timed_out":
            problems.append("deadline case g-late did not time out "
                            f"(got {statuses.get('g-late')})")

        # bit-parity oracle: every completed rollout vs a fresh solo run
        roll_specs = [s for s in request_mix(quick)
                      if s["kind"] == "rollout"
                      and statuses.get(s["request_id"]) == "completed"]
        ref = _reference_digests(roll_specs)
        mismatches = [
            rid for rid, dig in ref.items()
            if int(values[rid]["value"]["digest"]) != dig]
        for rid in mismatches:
            problems.append(f"resume digest mismatch for {rid}")
        bit_identical = not mismatches and bool(ref)

    row = {
        "name": "serve_soak",
        "n": 8,                      # largest rollout shape in the mix
        "backend": _backend(),
        "tenants": len(TENANTS),
        "accepted": accepted,
        "completed": completed,
        "rejected": len(client["rejected"]),
        "preempted": preempted,
        "timed_out": timed_out,
        "failed": failed,
        "silent_losses": silent,
        "resumed": resumed,
        "sigkills": 1,
        "resume_bit_identical": bit_identical,
        "latency_s": {
            "p50": round(float(np.percentile(lat, 50)), 3),
            "p95": round(float(np.percentile(lat, 95)), 3),
            "p99": round(float(np.percentile(lat, 99)), 3),
        },
        "wall_s": round(time.time() - t_start, 1),
        "quick": bool(quick),
    }
    print(json.dumps(row, indent=1))
    if problems:
        print(f"SOAK FAILED ({len(problems)} broken promise(s)):")
        for p in problems:
            print(f"  {p}")
        return 1
    if out:
        p = Path(out)
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(json.dumps(row, indent=1) + "\n")
        print(f"wrote {p}")
    return 0


def _backend() -> str:
    import jax
    return jax.default_backend()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--child", action="store_true",
                    help="(internal) one service lifetime")
    ap.add_argument("--dir", default=None,
                    help="(internal) journal directory")
    ap.add_argument("--quick", action="store_true",
                    help="smaller mix (CI smoke; artifact not committed)")
    ap.add_argument("--out", default=str(RESULTS / "serve_soak.json"),
                    help="artifact path ('' to skip writing)")
    args = ap.parse_args(argv)
    if args.child:
        return child(args.dir, args.quick)
    return run_soak(args.out or None, args.quick)


if __name__ == "__main__":
    sys.exit(main())
