"""Tiny schema guard for committed benchmark artifacts.

Committed `benchmarks/results/*.json` artifacts are load-bearing
evidence; silent schema drift (a renamed key, a row without its metric
value) turns them into dead weight that downstream tooling mis-parses
quietly. This checker fails LOUDLY instead.

Two artifact shapes exist:

- **row files** (JSON-lines, one object per line — the benchmark
  drivers' format): every row must carry a *name* (the ``name`` key; the
  pre-faults artifacts' ``metric`` key is accepted as the legacy alias)
  and either a numeric ``value`` or an ``error`` string (recorded
  environment failures are evidence too, see flood_sweep.json). When an
  ``n`` key is present it must be a positive integer. Artifacts written
  by `faults_suite.py` (fault_recovery.json) are held to the strict
  new-style schema: ``{name, n, value}`` on every row.
- **summary files** (a single JSON object, e.g. trials_summary.json):
  must parse and be a dict; their internal schema belongs to their
  producer.

Run standalone (CI / pre-commit):

    python benchmarks/check_results.py          # exit 1 + report on drift

or via the tier-1 test `tests/test_results_schema.py`.
"""
from __future__ import annotations

import json
import math
import sys
from pathlib import Path

RESULTS = Path(__file__).resolve().parent / "results"

# artifacts held to the strict {name, n, value} row schema (new-style;
# everything the faults subsystem and later suites commit goes here)
STRICT_ROWS = ("fault_recovery.json", "resilience_overhead.json")

# the serve-soak artifact (benchmarks/serve_soak.py; docs/SERVICE.md) is
# summary-shaped but schema-FIXED: the exact key set below, counted
# promises that must reconcile, and finite latency percentiles. A soak
# row that drifts (a renamed counter, a NaN percentile, counts that no
# longer add up to "every accepted request terminated") is rejected —
# it is the zero-silent-loss evidence, so drift here is evidence rot.
SERVE_SOAK = "serve_soak.json"
_SOAK_COUNTS = ("accepted", "completed", "rejected", "preempted",
                "timed_out", "failed", "silent_losses", "resumed",
                "sigkills", "tenants")
_SOAK_KEYS = set(_SOAK_COUNTS) | {"name", "n", "backend",
                                  "resume_bit_identical", "latency_s",
                                  "wall_s", "quick"}
_SOAK_PCTS = ("p50", "p95", "p99")

# the multi-worker chaos-soak artifact (benchmarks/
# serve_multiworker_soak.py; docs/SERVICE.md §multi-worker): summary-
# shaped, exact key set, counted promises that must reconcile, and the
# acceptance criteria baked in as schema — N>=3 workers, repeated
# single-worker kills, zero silent losses, >=1 bit-identical migrated
# resume, fairness preserved. An artifact that stops proving those is
# rejected, not quietly re-interpreted.
SERVE_MW_SOAK = "serve_multiworker_soak.json"
_MW_COUNTS = ("accepted", "completed", "rejected", "preempted",
              "timed_out", "failed", "poisoned", "silent_losses",
              "worker_kills", "requeued", "migrated_resumes",
              "tenants", "workers")
_MW_KEYS = set(_MW_COUNTS) | {"name", "n", "backend",
                              "migrated_bit_identical", "fairness_ok",
                              "latency_s", "wall_s", "quick"}

# the serve_throughput artifact (benchmarks/serve_throughput.py; ROADMAP
# open item 2(c)): JSON-lines, one row per offered-load level, exact key
# set — request Hz vs batch-bucket occupancy is the continuous-batching
# evidence, so a silently dropped occupancy column is evidence rot
SERVE_THROUGHPUT = "serve_throughput.json"
_THROUGHPUT_KEYS = {"name", "n", "backend", "offered_hz", "value",
                    "unit", "speedup", "stage_fracs", "host_frac",
                    "occupancy_mean", "occupancy_p95",
                    "queue_depth_mean", "queue_depth_p95", "accepted",
                    "completed", "rejected", "preempted",
                    "deadline_miss", "wall_s", "quick"}
_THROUGHPUT_COUNTS = ("accepted", "completed", "rejected", "preempted",
                      "deadline_miss")
# minimum committed offered-load levels (the acceptance criterion)
_THROUGHPUT_MIN_LEVELS = 3
# the PR-11 acceptance bar AS schema: at least one committed
# offered-load level must show the >= 3x single-worker req/s jump over
# the PR-7 capture on the same host (serve_throughput.py::R7_BASELINE_HZ
# — rows with no baseline for their level carry speedup 0.0)
_THROUGHPUT_SPEEDUP_BAR = 3.0
# per-round stage attribution carried alongside req/s (PR-11: the
# throughput jump must be attributable to the host-stage collapse in
# ONE artifact); fractions of span_serve.round_s, breakdown convention
_THROUGHPUT_STAGES = {"pack", "stack", "dispatch", "device_sync",
                      "unpack", "resolve"}

# the swarmtrace soak artifact (benchmarks/trace_soak.py;
# docs/OBSERVABILITY.md §swarmtrace): summary-shaped, exact key set,
# and the ISSUE-9 acceptance bars baked in AS schema — every accepted
# request of the traced multi-worker kill soak must reconstruct from
# the journal alone to a complete, gap-free timeline, and the
# serve-path tracing overhead must stay under 2%. An artifact that
# stops proving that is rejected, not quietly re-interpreted.
TRACE_SOAK = "trace_soak.json"
_TRACE_COUNTS = ("accepted", "completed", "timed_out", "failed",
                 "worker_kills", "migrated", "poisoned", "reconstructed",
                 "complete", "gap_free", "timeline_events",
                 "duplicate_chunks", "workers", "tenants")
_TRACE_KEYS = set(_TRACE_COUNTS) | {"name", "n", "backend",
                                    "trace_overhead_frac", "wall_s",
                                    "quick"}
_TRACE_OVERHEAD_BAR = 0.02

# the serve latency-breakdown artifact (benchmarks/
# serve_latency_breakdown.py): JSON-lines, one row per serve.round
# stage (round + pack/stack/dispatch/device_sync/unpack/resolve), exact
# key set — the per-stage wall attribution the throughput attack
# starts from, so a silently dropped stage is evidence rot
SERVE_BREAKDOWN = "serve_latency_breakdown.json"
_STAGE_KEYS = {"name", "stage", "n", "backend", "count", "value",
               "unit", "p50_s", "p95_s", "p99_s", "sum_s", "frac_round",
               "quick"}
_STAGE_SET = {"round", "pack", "stack", "dispatch", "device_sync",
              "unpack", "resolve"}
# the PR-11 acceptance bar AS schema: the host-side stages of the
# committed breakdown must stay BELOW half the round — the staged
# device-bound path collapsed pack 36% / stack 24% / unpack 30% (the
# PR-9 capture) and an artifact that drifts back to host-bound rounds
# is a regression, not a new baseline
_HOST_STAGES = ("pack", "stack", "unpack")
_HOST_FRAC_BAR = 0.5

# the scenario-suite artifact (benchmarks/scenario_suite.py;
# docs/SCENARIOS.md): JSON-lines, TWO rows per scenario family —
# completion (fraction of seeded trials that reconverged after
# everything the family scripted) and recovery (ticks from the last
# scenario event to reconvergence). Exact key set; NaN/Inf rejected;
# both kinds owed per family; a committed (non-quick) artifact owes a
# minimum family spread — a scenario vocabulary that quietly shrinks
# is evidence rot.
SCENARIO_SUITE = "scenario_suite.json"
_SCEN_KEYS = {"name", "kind", "n", "family", "trials", "seed", "ticks",
              "events", "wall_s", "device", "quick", "unit", "value"}
_SCEN_KINDS = ("completion", "recovery")
_SCEN_MIN_FAMILIES = 4


# the warm-pipeline artifact (benchmarks/pipeline_rate.py; ROADMAP
# open item 1): JSON-lines, three row kinds — warm-vs-cold ADMM across
# dispatches, the CBAA churn/lag hysteresis curve, and composed/host
# pipeline rates. The acceptance criteria ARE the schema: warm ADMM
# must re-converge in >= 3x fewer iterations than cold, the
# hysteresis-off run must be BITWISE identical to the default engine
# (baseline_parity — the zero-cost-off proof at artifact level), and
# the committed artifact owes the headline row: a warm-gains n=1000
# pipeline rate >= 100 Hz.
PIPELINE = "pipeline_n1000.json"
_PIPE_ADMM_KEYS = {"name", "n", "backend", "cold_iters", "warm_iters",
                   "iters_speedup", "cold_ms", "warm_ms", "time_speedup",
                   "gains_maxdiff", "quick"}
_PIPE_CHURN_KEYS = {"name", "n", "assignment", "warm_tables",
                    "assign_eps", "assign_every", "rematch_every",
                    "drift_speed", "ticks", "auctions", "reassigns",
                    "churn_rate", "lag_rms_m", "baseline_parity",
                    "quick"}
_PIPE_RATE_KEYS = {"name", "n", "mode", "backend", "assignment",
                   "assign_every", "redesign_every", "ticks",
                   "warm_gains", "tick_ms", "stage_ms", "gains_source",
                   "value", "unit", "quick"}
_PIPE_STAGES = {"tick", "assign", "gains"}
_PIPE_WARM_ITERS_BAR = 3.0
_PIPE_HEADLINE_N = 1000
_PIPE_HEADLINE_HZ = 100.0


def check_pipeline_n1000(rows: list, where: str) -> list[str]:
    """Validate pipeline_n1000 rows: exact key set per row kind, finite
    values, the >= 3x warm-iteration bar, the bitwise hysteresis-off
    parity row, and the n=1000 >= 100 Hz headline on committed
    artifacts."""
    probs = []
    all_quick = True
    saw_warm_bar = saw_parity = saw_headline = False
    for i, row in enumerate(rows, 1):
        at = f"{where}:{i}"
        if not isinstance(row, dict):
            probs.append(f"{at}: row is not a JSON object")
            continue
        name = row.get("name")
        keys = {"admm_warm_start": _PIPE_ADMM_KEYS,
                "assign_churn": _PIPE_CHURN_KEYS,
                "pipeline_rate": _PIPE_RATE_KEYS}.get(name)
        if keys is None:
            probs.append(f"{at}: 'name' must be admm_warm_start, "
                         f"assign_churn or pipeline_rate, got {name!r}")
            continue
        missing, unknown = keys - set(row), set(row) - keys
        if missing:
            probs.append(f"{at}: missing keys {sorted(missing)}")
        if unknown:
            probs.append(f"{at}: unknown keys {sorted(unknown)} "
                         "(exact-key-set schema)")
        if not (_is_count(row.get("n")) and row.get("n", 0) > 0):
            probs.append(f"{at}: 'n' must be a positive int")
        if not isinstance(row.get("quick"), bool):
            probs.append(f"{at}: 'quick' must be a bool")
        all_quick = all_quick and bool(row.get("quick"))
        if name == "admm_warm_start":
            for k in ("iters_speedup", "cold_ms", "warm_ms",
                      "time_speedup", "gains_maxdiff"):
                if k in row and not _finite_num(row[k]):
                    probs.append(f"{at}: '{k}' must be a finite number, "
                                 f"got {row[k]!r}")
            for k in ("cold_iters", "warm_iters"):
                if k in row and not (_is_count(row[k]) and row[k] > 0):
                    probs.append(f"{at}: '{k}' must be a positive int")
            sp = row.get("iters_speedup")
            if _finite_num(sp) and sp >= _PIPE_WARM_ITERS_BAR:
                saw_warm_bar = True
        elif name == "assign_churn":
            for k in ("assign_eps", "drift_speed", "churn_rate",
                      "lag_rms_m"):
                if k in row and not _finite_num(row[k]):
                    probs.append(f"{at}: '{k}' must be a finite number, "
                                 f"got {row[k]!r}")
            cr = row.get("churn_rate")
            if _finite_num(cr) and not 0.0 <= cr <= 1.0:
                probs.append(f"{at}: 'churn_rate' must be within [0, 1], "
                             f"got {cr!r}")
            for k in ("auctions", "reassigns", "assign_every",
                      "rematch_every", "ticks"):
                if k in row and not _is_count(row[k]):
                    probs.append(f"{at}: '{k}' must be a non-negative "
                                 "int")
            for k in ("warm_tables", "baseline_parity"):
                if k in row and not isinstance(row[k], bool):
                    probs.append(f"{at}: '{k}' must be a bool")
            if (row.get("warm_tables") is False
                    and row.get("assign_eps") == 0.0):
                if row.get("baseline_parity") is True:
                    saw_parity = True
                elif not row.get("quick"):
                    probs.append(
                        f"{at}: the hysteresis-off row (warm_tables "
                        "false, assign_eps 0) must be bitwise-identical "
                        "to the default engine (baseline_parity true) — "
                        "the off position IS today's engine")
        else:  # pipeline_rate
            if row.get("mode") not in ("host", "composed"):
                probs.append(f"{at}: 'mode' must be 'host' or "
                             f"'composed', got {row.get('mode')!r}")
            if row.get("unit") != "Hz":
                probs.append(f"{at}: 'unit' must be 'Hz'")
            if not isinstance(row.get("warm_gains"), bool):
                probs.append(f"{at}: 'warm_gains' must be a bool")
            v = row.get("value")
            if not (_finite_num(v) and v > 0):
                probs.append(f"{at}: 'value' must be a finite positive "
                             f"number, got {v!r}")
            if not isinstance(row.get("gains_source"), str):
                probs.append(f"{at}: 'gains_source' must name where the "
                             "gain term came from")
            sm = row.get("stage_ms")
            if not (isinstance(sm, dict) and set(sm) == _PIPE_STAGES
                    and all(_finite_num(x) and x >= 0
                            for x in sm.values())):
                probs.append(f"{at}: 'stage_ms' must map exactly "
                             f"{sorted(_PIPE_STAGES)} to finite "
                             "non-negative numbers")
            if (row.get("n") == _PIPE_HEADLINE_N
                    and row.get("warm_gains") is True
                    and _finite_num(v) and v >= _PIPE_HEADLINE_HZ):
                saw_headline = True
    if rows and not all_quick:
        if not saw_warm_bar:
            probs.append(
                f"{where}: no admm_warm_start row meets the "
                f">= {_PIPE_WARM_ITERS_BAR}x warm-iteration speedup — "
                "the warm start stopped paying for itself")
        if not saw_parity:
            probs.append(
                f"{where}: no hysteresis-off bitwise-parity row "
                "(warm_tables false, assign_eps 0, baseline_parity "
                "true) — the zero-cost-off proof is owed")
        if not saw_headline:
            probs.append(
                f"{where}: no warm-gains n={_PIPE_HEADLINE_N} "
                f"pipeline_rate row >= {_PIPE_HEADLINE_HZ} Hz — the "
                "ROADMAP item 1 headline is owed")
    return probs


def check_scenario_suite(rows: list, where: str) -> list[str]:
    """Validate scenario_suite rows: exact key set per kind, finite
    values (completion in [0, 1], recovery int >= -1 with a consistent
    'recovered' flag), both kinds per family, and the minimum family
    spread on committed artifacts."""
    probs = []
    fams: dict = {}
    all_quick = True
    for i, row in enumerate(rows, 1):
        at = f"{where}:{i}"
        if not isinstance(row, dict):
            probs.append(f"{at}: row is not a JSON object")
            continue
        kind = row.get("kind")
        if kind not in _SCEN_KINDS:
            probs.append(f"{at}: 'kind' must be one of {_SCEN_KINDS}, "
                         f"got {kind!r}")
            continue
        keys = _SCEN_KEYS | ({"recovered"} if kind == "recovery"
                             else set())
        missing, unknown = keys - set(row), set(row) - keys
        if missing:
            probs.append(f"{at}: missing keys {sorted(missing)}")
        if unknown:
            probs.append(f"{at}: unknown keys {sorted(unknown)} "
                         "(exact-key-set schema)")
        fam = row.get("family")
        if not isinstance(fam, str) or not fam:
            probs.append(f"{at}: 'family' must be a non-empty string")
            fam = None
        if fam is not None and row.get("name") != f"scenario_{fam}_{kind}":
            probs.append(f"{at}: 'name' must be 'scenario_{fam}_{kind}', "
                         f"got {row.get('name')!r}")
        v = row.get("value")
        if not _finite_num(v):
            probs.append(f"{at}: 'value' must be a finite number, "
                         f"got {v!r}")
        elif kind == "completion":
            if row.get("unit") != "frac":
                probs.append(f"{at}: completion 'unit' must be 'frac'")
            if not 0.0 <= v <= 1.0:
                probs.append(f"{at}: completion must be within [0, 1], "
                             f"got {v!r}")
        else:
            if row.get("unit") != "ticks":
                probs.append(f"{at}: recovery 'unit' must be 'ticks'")
            if not (isinstance(v, int) and v >= -1):
                probs.append(f"{at}: recovery must be an int >= -1 "
                             f"(-1 = never recovered), got {v!r}")
            recd = row.get("recovered")
            if not isinstance(recd, bool):
                probs.append(f"{at}: 'recovered' must be a bool")
            elif isinstance(v, int) and recd != (v >= 0):
                probs.append(f"{at}: 'recovered' ({recd}) inconsistent "
                             f"with value ({v})")
        for k in ("n", "trials", "ticks"):
            if k in row and not (_is_count(row[k]) and row[k] > 0):
                probs.append(f"{at}: '{k}' must be a positive int, "
                             f"got {row[k]!r}")
        if "events" in row and not _is_count(row["events"]):
            probs.append(f"{at}: 'events' must be a non-negative int")
        if "wall_s" in row and not (_finite_num(row["wall_s"])
                                    and row["wall_s"] >= 0):
            probs.append(f"{at}: 'wall_s' must be a finite non-negative "
                         "number")
        if "quick" in row and not isinstance(row["quick"], bool):
            probs.append(f"{at}: 'quick' must be a bool")
        all_quick = all_quick and bool(row.get("quick"))
        if fam is not None:
            fams.setdefault(fam, set()).add(kind)
    for fam, kinds in fams.items():
        missing_kinds = set(_SCEN_KINDS) - kinds
        if missing_kinds:
            probs.append(f"{where}: family {fam!r} missing "
                         f"{sorted(missing_kinds)} row(s) — every "
                         "family owes completion AND recovery")
    # the family-spread bar is waived ONLY for an all-quick smoke
    # artifact: one stray quick row must not exempt a committed
    # (non-quick) artifact whose vocabulary shrank
    if rows and not all_quick and len(fams) < _SCEN_MIN_FAMILIES:
        probs.append(
            f"{where}: only {len(fams)} scenario family(ies); the "
            f"committed artifact owes >= {_SCEN_MIN_FAMILIES} "
            "(the scenario vocabulary must not silently shrink)")
    return probs


# the serve_overload artifact (benchmarks/serve_overload.py; ROADMAP
# open item 3): JSON-lines, one row per offered-load level driven by
# the adversarial open-loop traffic fleet over the TCP front end. The
# acceptance criteria ARE the schema: >= 4 committed levels up to 10x
# measured capacity, ZERO silent losses on every row (every accepted
# request journal-attributable), goodput at 10x holding >= 90% of
# goodput at 1x (admission sheds load instead of collapsing), and a
# real shed at 10x (rejects > 0 — otherwise "capacity" was mismeasured
# and the 10x level proves nothing).
SERVE_OVERLOAD = "serve_overload.json"
_OVERLOAD_COUNTS = ("offered", "accepted", "completed", "timed_out",
                    "cancelled", "shed", "wire_lost", "failed_other",
                    "server_rejected", "retry_submits",
                    "accepted_after_retry", "silent_losses",
                    "pm_complete", "pm_reconstructed", "crc_rejected",
                    "slowloris_dropped", "reconnects", "unresolved")
_OVERLOAD_KEYS = set(_OVERLOAD_COUNTS) | {
    "name", "level", "multiplier", "n", "backend", "capacity_hz",
    "offered_hz", "value", "unit", "p50_s", "p99_s", "reject_rate",
    "retry_after_p50", "wall_s", "quick"}
_OVERLOAD_MIN_LEVELS = 4
_OVERLOAD_MAX_MULT = 10.0
_OVERLOAD_GOODPUT_FRAC = 0.9


def check_serve_overload(rows: list, where: str) -> list[str]:
    """Validate serve_overload rows: exact key set, reconciling
    counts, and the overload acceptance bars AS schema."""
    probs = []
    by_mult: dict = {}
    any_committed = False
    for i, row in enumerate(rows, 1):
        at = f"{where}:{i}"
        if not isinstance(row, dict):
            probs.append(f"{at}: row is not a JSON object")
            continue
        missing, unknown = _OVERLOAD_KEYS - set(row), \
            set(row) - _OVERLOAD_KEYS
        if missing:
            probs.append(f"{at}: missing keys {sorted(missing)}")
        if unknown:
            probs.append(f"{at}: unknown keys {sorted(unknown)} "
                         "(exact-key-set schema)")
        if row.get("name") != "serve_overload":
            probs.append(f"{at}: 'name' must be 'serve_overload'")
        if row.get("unit") != "Hz":
            probs.append(f"{at}: 'unit' must be 'Hz'")
        for k in _OVERLOAD_COUNTS:
            if k in row and not _is_count(row[k]):
                probs.append(f"{at}: '{k}' must be a non-negative int, "
                             f"got {row[k]!r}")
        for k in ("multiplier", "capacity_hz", "offered_hz", "value",
                  "p50_s", "p99_s", "retry_after_p50", "wall_s"):
            if k in row and not (_finite_num(row[k]) and row[k] >= 0):
                probs.append(f"{at}: '{k}' must be a finite non-negative"
                             f" number, got {row[k]!r}")
        if "reject_rate" in row and not (
                _finite_num(row["reject_rate"])
                and 0.0 <= row["reject_rate"] <= 1.0):
            probs.append(f"{at}: 'reject_rate' must be within [0, 1]")
        if "quick" in row and not isinstance(row["quick"], bool):
            probs.append(f"{at}: 'quick' must be a bool")
        # the ledger must reconcile: every offered arrival is completed,
        # timed out, cancelled, shed, or still counted unresolved (and
        # unresolved must be zero)
        if all(_is_count(row.get(k)) for k in
               ("offered", "completed", "timed_out", "cancelled",
                "shed", "wire_lost", "failed_other", "unresolved")):
            total = (row["completed"] + row["timed_out"]
                     + row["cancelled"] + row["shed"]
                     + row["wire_lost"] + row["failed_other"]
                     + row["unresolved"])
            if total != row["offered"]:
                probs.append(
                    f"{at}: offered ({row['offered']}) != completed + "
                    f"timed_out + cancelled + shed + wire_lost + "
                    f"failed_other + unresolved ({total}) — the client "
                    "ledger must reconcile")
        if row.get("silent_losses") not in (0, None):
            probs.append(f"{at}: silent_losses must be 0 — an accepted "
                         "request without a journal-attributable "
                         "terminal state is the one forbidden outcome "
                         f"(got {row.get('silent_losses')!r})")
        if row.get("unresolved") not in (0, None):
            probs.append(f"{at}: unresolved must be 0 (got "
                         f"{row.get('unresolved')!r})")
        if _is_count(row.get("pm_complete")) \
                and _is_count(row.get("pm_reconstructed")) \
                and row["pm_complete"] != row["pm_reconstructed"]:
            probs.append(f"{at}: postmortem attributed "
                         f"{row['pm_complete']} of "
                         f"{row['pm_reconstructed']} timelines — every "
                         "accepted request must reconstruct complete")
        if _finite_num(row.get("multiplier")):
            by_mult[row["multiplier"]] = row
            any_committed = any_committed or not row.get("quick")
    committed = {m: r for m, r in by_mult.items() if not r.get("quick")}
    if rows and any_committed:
        if len(committed) < _OVERLOAD_MIN_LEVELS:
            probs.append(
                f"{where}: only {len(committed)} committed offered-load"
                f" level(s); the artifact owes >= "
                f"{_OVERLOAD_MIN_LEVELS} (0.5x..10x)")
        if committed and max(committed) < _OVERLOAD_MAX_MULT:
            probs.append(
                f"{where}: highest committed level is "
                f"{max(committed):g}x; the overload proof owes >= "
                f"{_OVERLOAD_MAX_MULT:g}x capacity")
        ten = committed.get(_OVERLOAD_MAX_MULT)
        one = committed.get(1.0)
        if ten is not None and one is not None \
                and _finite_num(ten.get("value")) \
                and _finite_num(one.get("value")) and one["value"] > 0:
            frac = ten["value"] / one["value"]
            if frac < _OVERLOAD_GOODPUT_FRAC:
                probs.append(
                    f"{where}: goodput at 10x is {frac:.1%} of goodput "
                    f"at 1x — below the {_OVERLOAD_GOODPUT_FRAC:.0%} "
                    "bar: admission is collapsing instead of shedding")
        if ten is not None and _is_count(ten.get("shed")) \
                and ten["shed"] == 0:
            probs.append(
                f"{where}: the 10x level shed nothing — either "
                "capacity was mismeasured or admission never engaged; "
                "the overload proof proves nothing")
    return probs


# the swarmrouter cross-process fleet artifact
# (benchmarks/router_fleet.py; docs/SERVICE.md §process mode): a
# p99-vs-offered-load curve measured from a client in its OWN OS
# process against a router supervising >= 2 procworker processes,
# plus exactly one rolling-restart drill row. The bars ride as
# schema: pairwise-distinct pids on every row (the separation is
# provenance, not prose), a reconciling client ledger with zero
# unresolved tickets, and a drill with >= 2 kills, >= 1 migration,
# sub-2 s detection, a bit-identical migrated probe, and ZERO
# journaled losses across the merged per-slot journals.
ROUTER_FLEET = "router_fleet.json"
_ROUTER_COUNTS = ("offered", "completed", "timed_out", "shed",
                  "cancelled", "wire_lost", "failed_other",
                  "unresolved", "client_pid", "router_pid")
_ROUTER_SHARED = set(_ROUTER_COUNTS) | {
    "name", "level", "multiplier", "n", "backend", "workers",
    "capacity_hz", "offered_hz", "value", "unit", "worker_pids",
    "separate_client_process", "wall_s", "quick"}
_ROUTER_LEVEL_KEYS = _ROUTER_SHARED | {
    "p50_s", "p99_s", "retry_submits"}
_ROUTER_DRILL_KEYS = _ROUTER_SHARED | {
    "kills", "migrations", "detection_ms_max", "readmitted",
    "restarts", "restart_drained", "restart_readmitted",
    "bit_identical", "probe_status", "probe_failovers",
    "journaled_losses", "duplicate_terminals", "pm_resolved",
    "pm_gap_free"}
_ROUTER_MIN_LEVELS = 3
_ROUTER_MIN_KILLS = 2
_ROUTER_DETECT_MS = 2000.0


def check_router_fleet(rows: list, where: str) -> list[str]:
    """Validate router_fleet rows: exact key sets (level vs drill
    shape), pid provenance, reconciling ledgers, and the drill
    acceptance bars AS schema."""
    probs = []
    levels: dict = {}
    drills: list = []
    any_committed = False
    for i, row in enumerate(rows, 1):
        at = f"{where}:{i}"
        if not isinstance(row, dict):
            probs.append(f"{at}: row is not a JSON object")
            continue
        is_drill = row.get("level") == "drill"
        want = _ROUTER_DRILL_KEYS if is_drill else _ROUTER_LEVEL_KEYS
        missing, unknown = want - set(row), set(row) - want
        if missing:
            probs.append(f"{at}: missing keys {sorted(missing)}")
        if unknown:
            probs.append(f"{at}: unknown keys {sorted(unknown)} "
                         "(exact-key-set schema)")
        if row.get("name") != "router_fleet":
            probs.append(f"{at}: 'name' must be 'router_fleet'")
        if row.get("unit") != ("kills" if is_drill else "Hz"):
            probs.append(f"{at}: 'unit' must be "
                         f"{'kills' if is_drill else 'Hz'!r}")
        for k in _ROUTER_COUNTS:
            if k in row and not _is_count(row[k]):
                probs.append(f"{at}: '{k}' must be a non-negative int, "
                             f"got {row[k]!r}")
        for k in ("multiplier", "capacity_hz", "offered_hz", "wall_s"):
            if k in row and not (_finite_num(row[k]) and row[k] >= 0):
                probs.append(f"{at}: '{k}' must be a finite "
                             f"non-negative number, got {row[k]!r}")
        if "quick" in row and not isinstance(row["quick"], bool):
            probs.append(f"{at}: 'quick' must be a bool")
        # pid provenance: the whole point of the artifact is that the
        # client, the router, and every worker are DIFFERENT processes
        pids = [row.get("client_pid"), row.get("router_pid"),
                *(row.get("worker_pids") or [])]
        if not isinstance(row.get("worker_pids"), list) \
                or len(row.get("worker_pids") or []) < 2:
            probs.append(f"{at}: 'worker_pids' must list >= 2 worker "
                         "processes")
        elif all(_is_count(p) for p in pids) \
                and len(set(pids)) != len(pids):
            probs.append(f"{at}: client/router/worker pids must be "
                         f"pairwise distinct, got {pids}")
        if row.get("separate_client_process") is not True:
            probs.append(f"{at}: 'separate_client_process' must be "
                         "true — the client fleet must run in its own "
                         "OS process")
        # the client ledger must reconcile
        if all(_is_count(row.get(k)) for k in
               ("offered", "completed", "timed_out", "shed",
                "cancelled", "wire_lost", "failed_other",
                "unresolved")):
            total = (row["completed"] + row["timed_out"] + row["shed"]
                     + row["cancelled"] + row["wire_lost"]
                     + row["failed_other"] + row["unresolved"])
            if total != row["offered"]:
                probs.append(
                    f"{at}: offered ({row['offered']}) != completed + "
                    f"timed_out + shed + cancelled + wire_lost + "
                    f"failed_other + unresolved ({total}) — the client "
                    "ledger must reconcile")
        if row.get("unresolved") not in (0, None):
            probs.append(f"{at}: unresolved must be 0 (got "
                         f"{row.get('unresolved')!r})")
        if is_drill:
            drills.append((at, row))
        elif _finite_num(row.get("multiplier")):
            levels[row["multiplier"]] = row
        any_committed = any_committed or not row.get("quick")
    for at, d in drills:
        if _is_count(d.get("kills")) \
                and d["kills"] < _ROUTER_MIN_KILLS:
            probs.append(f"{at}: drill killed {d['kills']} worker(s); "
                         f"the bar is >= {_ROUTER_MIN_KILLS} (one per "
                         "slot, staggered)")
        if _is_count(d.get("migrations")) and d["migrations"] < 1:
            probs.append(f"{at}: drill migrated 0 in-flight routes — "
                         "a kill that lands on an idle process proves "
                         "nothing about failover")
        det = d.get("detection_ms_max")
        if det is not None and _finite_num(det) \
                and det >= _ROUTER_DETECT_MS:
            probs.append(f"{at}: worst kill->declared-dead detection "
                         f"{det:g} ms breaches the "
                         f"{_ROUTER_DETECT_MS:g} ms bar")
        if d.get("journaled_losses") != 0:
            probs.append(f"{at}: journaled_losses must be 0 — an "
                         "accepted request terminal in NO slot journal "
                         "is the one forbidden outcome (got "
                         f"{d.get('journaled_losses')!r})")
        if d.get("bit_identical") is not True:
            probs.append(f"{at}: the migrated probe must resume "
                         "bit-identical (probe_status="
                         f"{d.get('probe_status')!r})")
        for k in ("readmitted", "restart_drained",
                  "restart_readmitted"):
            if d.get(k) is not True:
                probs.append(f"{at}: '{k}' must be true — the rolling "
                             "restart must re-admit every slot")
    if rows and any_committed:
        committed = {m: r for m, r in levels.items()
                     if not r.get("quick")}
        if len(committed) < _ROUTER_MIN_LEVELS:
            probs.append(
                f"{where}: only {len(committed)} committed offered-"
                f"load level(s); the curve owes >= "
                f"{_ROUTER_MIN_LEVELS}")
        n_drill = sum(1 for _, d in drills if not d.get("quick"))
        if n_drill != 1:
            probs.append(f"{where}: exactly one committed drill row "
                         f"required, found {n_drill}")
    return probs


# the swarmwatch SLO-detection artifact (benchmarks/slo_soak.py;
# docs/OBSERVABILITY.md §swarmwatch): summary-shaped, exact key set,
# and the ISSUE-15 acceptance bars baked in AS schema — every scripted
# worker kill detected (a worker_up alert fired, or was already firing
# from a repeated kill inside the clear dwell) within the committed
# bound, ZERO false-positive alerts in the clean control soak, sampler
# overhead under 2% of soak wall, and the persisted time-series
# history actually readable from disk. An artifact that stops proving
# detection is rejected, not quietly re-interpreted.
SLO_DETECTION = "slo_detection.json"
_SLO_COUNTS = ("workers", "tenants", "accepted", "completed",
               "silent_losses", "kills", "detected", "already_firing",
               "alerts_fired", "alerts_resolved", "sampler_samples",
               "persist_lost", "persisted_ticks", "series",
               "control_accepted", "control_completed",
               "false_positives")
_SLO_KEYS = set(_SLO_COUNTS) | {"name", "n", "backend", "detection_s",
                                "bound_s", "watch_interval_s",
                                "sampler_overhead_frac",
                                "control_overhead_frac", "wall_s",
                                "quick"}
_SLO_DETECTION_PCTS = ("p50", "p95", "max")
_SLO_OVERHEAD_BAR = 0.02
_SLO_BOUND_CAP_S = 5.0


def check_slo_detection(obj, where: str) -> list[str]:
    """Validate the slo_detection summary: exact key set, and the
    detection acceptance bars AS schema (100% of kills detected within
    the bound, zero control false positives, <2% sampler overhead,
    history persisted)."""
    if not isinstance(obj, dict):
        return [f"{where}: not a JSON object"]
    probs = []
    missing, unknown = _SLO_KEYS - set(obj), set(obj) - _SLO_KEYS
    if missing:
        probs.append(f"{where}: missing keys {sorted(missing)}")
    if unknown:
        probs.append(f"{where}: unknown keys {sorted(unknown)} "
                     "(exact-key-set schema)")
    if obj.get("name") != "slo_detection":
        probs.append(f"{where}: 'name' must be 'slo_detection'")
    for k in _SLO_COUNTS:
        if k in obj and not _is_count(obj[k]):
            probs.append(f"{where}: '{k}' must be a non-negative int, "
                         f"got {obj[k]!r}")
    if _is_count(obj.get("kills")) and _is_count(obj.get("detected")) \
            and obj["detected"] != obj["kills"]:
        probs.append(
            f"{where}: detected ({obj['detected']}) != kills "
            f"({obj['kills']}) — EVERY scripted kill must raise (or "
            "land inside) a worker_up alert (the acceptance bar)")
    for k in ("silent_losses", "false_positives", "persist_lost"):
        if obj.get(k) not in (0, None):
            probs.append(f"{where}: {k} must be 0 (got {obj.get(k)!r})")
    for pair in (("completed", "accepted"),
                 ("control_completed", "control_accepted")):
        if all(_is_count(obj.get(k)) for k in pair) \
                and obj[pair[0]] != obj[pair[1]]:
            probs.append(f"{where}: {pair[0]} ({obj[pair[0]]}) != "
                         f"{pair[1]} ({obj[pair[1]]}) — the soak mix "
                         "must fully complete")
    if _is_count(obj.get("persisted_ticks")) \
            and obj["persisted_ticks"] < 1:
        probs.append(f"{where}: persisted_ticks must be >= 1 — the "
                     "history must be readable from disk alone")
    bound = obj.get("bound_s")
    if not (_finite_num(bound) and 0 < bound <= _SLO_BOUND_CAP_S):
        probs.append(f"{where}: 'bound_s' must be a finite number in "
                     f"(0, {_SLO_BOUND_CAP_S}], got {bound!r} — "
                     "'bounded latency' means a real bound")
    det = obj.get("detection_s")
    if not isinstance(det, dict):
        probs.append(f"{where}: 'detection_s' must be an object")
    else:
        miss = set(_SLO_DETECTION_PCTS) - set(det)
        unk = set(det) - set(_SLO_DETECTION_PCTS)
        if miss:
            probs.append(f"{where}: detection_s missing {sorted(miss)}")
        if unk:
            probs.append(f"{where}: detection_s unknown keys "
                         f"{sorted(unk)}")
        vals = [det.get(k) for k in _SLO_DETECTION_PCTS]
        for k, v in zip(_SLO_DETECTION_PCTS, vals):
            if v is not None and not (_finite_num(v) and v >= 0):
                probs.append(f"{where}: detection_s.{k} must be a "
                             f"finite non-negative number, got {v!r}")
        if all(_finite_num(v) and v >= 0 for v in vals):
            if not (vals[0] <= vals[1] <= vals[2]):
                probs.append(f"{where}: detection percentiles must be "
                             f"non-decreasing, got {vals}")
            if _finite_num(bound) and vals[2] > bound:
                probs.append(
                    f"{where}: max detection latency {vals[2]} s over "
                    f"the committed {bound} s bound — detection is not "
                    "bounded")
    for k in ("sampler_overhead_frac", "control_overhead_frac"):
        v = obj.get(k)
        if not (_finite_num(v) and v >= 0):
            probs.append(f"{where}: '{k}' must be a finite non-negative "
                         f"number, got {v!r}")
        elif v >= _SLO_OVERHEAD_BAR:
            probs.append(f"{where}: {k} {v} breaches the < "
                         f"{_SLO_OVERHEAD_BAR} acceptance bar")
    if "watch_interval_s" in obj and not (
            _finite_num(obj["watch_interval_s"])
            and obj["watch_interval_s"] > 0):
        probs.append(f"{where}: 'watch_interval_s' must be a positive "
                     "number")
    if "quick" in obj and not isinstance(obj["quick"], bool):
        probs.append(f"{where}: 'quick' must be a bool")
    if not obj.get("quick"):
        # the committed (non-quick) artifact IS the acceptance evidence
        if _is_count(obj.get("workers")) and obj["workers"] < 3:
            probs.append(f"{where}: committed soak needs >= 3 workers, "
                         f"got {obj['workers']}")
        if _is_count(obj.get("kills")) and obj["kills"] < 3:
            probs.append(f"{where}: committed soak owes >= 3 scripted "
                         f"kills, got {obj.get('kills')}")
        if _is_count(obj.get("alerts_resolved")) \
                and obj["alerts_resolved"] < 1:
            probs.append(f"{where}: committed soak recorded no resolved "
                         "alert — the state machine never closed")
    if "wall_s" in obj and not (_finite_num(obj["wall_s"])
                                and obj["wall_s"] >= 0):
        probs.append(f"{where}: 'wall_s' must be a finite non-negative "
                     f"number, got {obj['wall_s']!r}")
    if "n" in obj and not (_is_count(obj["n"]) and obj["n"] > 0):
        probs.append(f"{where}: 'n' must be a positive int")
    return probs


# the telemetry overhead artifact (aclswarm_tpu.telemetry.overhead):
# exact key set per named row, and the <5% acceptance bar is part of
# the schema — an artifact showing a regression must not pass silently
TELEMETRY_OVERHEAD = "telemetry_overhead.json"
_OVERHEAD_KEYS = {
    "telemetry_overhead_frac_n10": {"name", "n", "value", "unit",
                                    "wall_off_s", "wall_on_s", "chunks",
                                    "reps", "note"},
    "telemetry_publish_us": {"name", "n", "value", "unit", "note"},
}
_OVERHEAD_BAR = 0.05


def _finite_num(v) -> bool:
    return (isinstance(v, (int, float)) and not isinstance(v, bool)
            and math.isfinite(v))


def check_serve_throughput(rows: list, where: str) -> list[str]:
    """Validate parsed serve_throughput rows (exact key set, count
    sanity, occupancy in [0, 1], >= 3 non-quick offered-load levels)."""
    probs = []
    levels = set()
    for i, row in enumerate(rows, 1):
        at = f"{where}:{i}"
        if not isinstance(row, dict):
            probs.append(f"{at}: row is not a JSON object")
            continue
        missing = _THROUGHPUT_KEYS - set(row)
        unknown = set(row) - _THROUGHPUT_KEYS
        if missing:
            probs.append(f"{at}: missing keys {sorted(missing)}")
        if unknown:
            probs.append(f"{at}: unknown keys {sorted(unknown)} "
                         "(exact-key-set schema)")
        if row.get("name") != "serve_throughput":
            probs.append(f"{at}: 'name' must be 'serve_throughput'")
        for k in ("offered_hz", "value", "wall_s", "queue_depth_mean",
                  "queue_depth_p95"):
            if k in row and not (_finite_num(row[k]) and row[k] >= 0):
                probs.append(f"{at}: '{k}' must be a finite non-negative "
                             f"number, got {row[k]!r}")
        for k in ("occupancy_mean", "occupancy_p95"):
            if k in row and not (_finite_num(row[k])
                                 and 0.0 <= row[k] <= 1.0):
                probs.append(f"{at}: '{k}' must be within [0, 1], got "
                             f"{row[k]!r}")
        if "speedup" in row and not (_finite_num(row["speedup"])
                                     and row["speedup"] >= 0):
            probs.append(f"{at}: 'speedup' must be a finite "
                         f"non-negative number, got {row['speedup']!r}")
        if "host_frac" in row and not (_finite_num(row["host_frac"])
                                       and 0.0 <= row["host_frac"]
                                       <= 1.0001):
            probs.append(f"{at}: 'host_frac' must be within [0, 1], "
                         f"got {row['host_frac']!r}")
        fr = row.get("stage_fracs")
        if "stage_fracs" in row:
            if not isinstance(fr, dict):
                probs.append(f"{at}: 'stage_fracs' must be an object")
            else:
                miss = _THROUGHPUT_STAGES - set(fr)
                unk = set(fr) - _THROUGHPUT_STAGES
                if miss:
                    probs.append(f"{at}: stage_fracs missing "
                                 f"{sorted(miss)}")
                if unk:
                    probs.append(f"{at}: stage_fracs unknown keys "
                                 f"{sorted(unk)}")
                for k, v in fr.items():
                    if not (_finite_num(v) and 0.0 <= v <= 1.0001):
                        probs.append(f"{at}: stage_fracs.{k} must be "
                                     f"within [0, 1], got {v!r}")
        for k in _THROUGHPUT_COUNTS:
            if k in row and not _is_count(row[k]):
                probs.append(f"{at}: '{k}' must be a non-negative int, "
                             f"got {row[k]!r}")
        if _is_count(row.get("accepted")) and _is_count(
                row.get("completed")) \
                and row["completed"] > row["accepted"]:
            probs.append(f"{at}: completed ({row['completed']}) > "
                         f"accepted ({row['accepted']})")
        if "quick" in row and not isinstance(row["quick"], bool):
            probs.append(f"{at}: 'quick' must be a bool")
        if _finite_num(row.get("offered_hz")) and not row.get("quick"):
            levels.add(row["offered_hz"])
    if len(levels) < _THROUGHPUT_MIN_LEVELS:
        probs.append(
            f"{where}: only {len(levels)} non-quick offered-load "
            f"level(s); the committed artifact owes >= "
            f"{_THROUGHPUT_MIN_LEVELS} (request Hz vs occupancy vs "
            "offered load)")
    non_quick = [r for r in rows if isinstance(r, dict)
                 and not r.get("quick")]
    if non_quick and not any(
            _finite_num(r.get("speedup"))
            and r["speedup"] >= _THROUGHPUT_SPEEDUP_BAR
            for r in non_quick):
        probs.append(
            f"{where}: no committed level shows the >= "
            f"{_THROUGHPUT_SPEEDUP_BAR:g}x single-worker req/s jump "
            "over the PR-7 capture (the PR-11 acceptance bar; "
            "'speedup' vs serve_throughput.py::R7_BASELINE_HZ)")
    return probs


def check_telemetry_overhead(rows: list, where: str) -> list[str]:
    """Validate parsed telemetry_overhead rows (exact key set per named
    row; the <5% acceptance bar on the n=10 fraction row)."""
    probs = []
    seen = set()
    for i, row in enumerate(rows, 1):
        at = f"{where}:{i}"
        if not isinstance(row, dict):
            probs.append(f"{at}: row is not a JSON object")
            continue
        name = row.get("name")
        keys = _OVERHEAD_KEYS.get(name)
        if keys is None:
            probs.append(f"{at}: unknown row name {name!r} (expected "
                         f"{sorted(_OVERHEAD_KEYS)})")
            continue
        seen.add(name)
        missing, unknown = keys - set(row), set(row) - keys
        if missing:
            probs.append(f"{at}: missing keys {sorted(missing)}")
        if unknown:
            probs.append(f"{at}: unknown keys {sorted(unknown)} "
                         "(exact-key-set schema)")
        if not (_finite_num(row.get("value")) and row.get("value") >= 0):
            probs.append(f"{at}: 'value' must be a finite non-negative "
                         f"number, got {row.get('value')!r}")
        elif name == "telemetry_overhead_frac_n10" \
                and row["value"] >= _OVERHEAD_BAR:
            probs.append(
                f"{at}: telemetry-on overhead {row['value']} breaches "
                f"the < {_OVERHEAD_BAR} acceptance bar "
                "(docs/OBSERVABILITY.md)")
    for name in _OVERHEAD_KEYS:
        if name not in seen:
            probs.append(f"{where}: missing required row {name!r}")
    return probs


LOCK_OVERHEAD = "lock_overhead.json"
_LOCK_KEYS = {
    "lock_overhead_frac_serve": {"name", "n", "value", "unit",
                                 "wall_plain_s", "wall_ordered_s",
                                 "reps", "note"},
    "lock_pair_ns": {"name", "n", "value", "unit", "plain_pair_ns",
                     "armed_pair_ns", "note"},
}
_LOCK_OVERHEAD_BAR = 0.02


def check_lock_overhead(rows: list, where: str) -> list[str]:
    """Validate parsed lock_overhead rows (exact key set per named row;
    the <2% swarmguard acceptance bar on the serve-round fraction —
    the lock DISCIPLINE must be free in production, only the armed
    debug mode is allowed to cost)."""
    probs = []
    seen = set()
    for i, row in enumerate(rows, 1):
        at = f"{where}:{i}"
        if not isinstance(row, dict):
            probs.append(f"{at}: row is not a JSON object")
            continue
        name = row.get("name")
        keys = _LOCK_KEYS.get(name)
        if keys is None:
            probs.append(f"{at}: unknown row name {name!r} (expected "
                         f"{sorted(_LOCK_KEYS)})")
            continue
        seen.add(name)
        missing, unknown = keys - set(row), set(row) - keys
        if missing:
            probs.append(f"{at}: missing keys {sorted(missing)}")
        if unknown:
            probs.append(f"{at}: unknown keys {sorted(unknown)} "
                         "(exact-key-set schema)")
        if not (_finite_num(row.get("value")) and row.get("value") >= 0):
            probs.append(f"{at}: 'value' must be a finite non-negative "
                         f"number, got {row.get('value')!r}")
        elif name == "lock_overhead_frac_serve" \
                and row["value"] >= _LOCK_OVERHEAD_BAR:
            probs.append(
                f"{at}: lock-tier serve overhead {row['value']} "
                f"breaches the < {_LOCK_OVERHEAD_BAR} acceptance bar "
                "(docs/OBSERVABILITY.md)")
    for name in _LOCK_KEYS:
        if name not in seen:
            probs.append(f"{where}: missing required row {name!r}")
    return probs


def _is_count(v) -> bool:
    return isinstance(v, int) and not isinstance(v, bool) and v >= 0


def check_trace_soak(obj, where: str) -> list[str]:
    """Validate the trace_soak summary: exact key set, reconciling
    counts, and the acceptance bars AS schema — 100% of accepted
    requests reconstructed complete + gap-free, kills/migrations/poison
    actually exercised, tracing overhead under the 2% bar."""
    if not isinstance(obj, dict):
        return [f"{where}: not a JSON object"]
    probs = []
    missing, unknown = _TRACE_KEYS - set(obj), set(obj) - _TRACE_KEYS
    if missing:
        probs.append(f"{where}: missing keys {sorted(missing)}")
    if unknown:
        probs.append(f"{where}: unknown keys {sorted(unknown)} "
                     "(exact-key-set schema)")
    if obj.get("name") != "trace_soak":
        probs.append(f"{where}: 'name' must be 'trace_soak'")
    for k in _TRACE_COUNTS:
        if k in obj and not _is_count(obj[k]):
            probs.append(f"{where}: '{k}' must be a non-negative int, "
                         f"got {obj[k]!r}")
    if all(_is_count(obj.get(k)) for k in
           ("accepted", "completed", "timed_out", "failed")):
        total = obj["completed"] + obj["timed_out"] + obj["failed"]
        if total != obj["accepted"]:
            probs.append(
                f"{where}: accepted ({obj['accepted']}) != completed + "
                f"timed_out + failed ({total}) — the terminal ledger "
                "must reconcile")
    acc = obj.get("accepted")
    if _is_count(acc):
        for k in ("reconstructed", "complete", "gap_free"):
            if _is_count(obj.get(k)) and obj[k] != acc:
                probs.append(
                    f"{where}: {k} ({obj[k]}) != accepted ({acc}) — "
                    "EVERY accepted request must reconstruct to a "
                    "complete, gap-free timeline (the acceptance bar)")
    ov = obj.get("trace_overhead_frac")
    if not (_finite_num(ov) and ov >= 0):
        probs.append(f"{where}: 'trace_overhead_frac' must be a finite "
                     f"non-negative number, got {ov!r}")
    elif ov >= _TRACE_OVERHEAD_BAR:
        probs.append(
            f"{where}: serve-path tracing overhead {ov} breaches the "
            f"< {_TRACE_OVERHEAD_BAR} acceptance bar")
    if "quick" in obj and not isinstance(obj["quick"], bool):
        probs.append(f"{where}: 'quick' must be a bool")
    if not obj.get("quick"):
        # the committed (non-quick) artifact IS the acceptance evidence
        if _is_count(obj.get("workers")) and obj["workers"] < 3:
            probs.append(f"{where}: committed soak needs >= 3 workers, "
                         f"got {obj['workers']}")
        for k in ("worker_kills", "migrated", "poisoned"):
            if _is_count(obj.get(k)) and obj[k] < 1:
                probs.append(f"{where}: committed soak recorded no "
                             f"{k} — the traced chaos never happened")
    if "wall_s" in obj and not (_finite_num(obj["wall_s"])
                                and obj["wall_s"] >= 0):
        probs.append(f"{where}: 'wall_s' must be a finite non-negative "
                     f"number, got {obj['wall_s']!r}")
    if "n" in obj and not (_is_count(obj["n"]) and obj["n"] > 0):
        probs.append(f"{where}: 'n' must be a positive int")
    return probs


def check_serve_latency_breakdown(rows: list, where: str) -> list[str]:
    """Validate serve_latency_breakdown rows: exact key set, the FULL
    stage set present, finite non-negative numbers, and the child
    stages summing to no more than the round they nest in."""
    probs = []
    seen = {}
    for i, row in enumerate(rows, 1):
        at = f"{where}:{i}"
        if not isinstance(row, dict):
            probs.append(f"{at}: row is not a JSON object")
            continue
        missing = _STAGE_KEYS - set(row)
        unknown = set(row) - _STAGE_KEYS
        if missing:
            probs.append(f"{at}: missing keys {sorted(missing)}")
        if unknown:
            probs.append(f"{at}: unknown keys {sorted(unknown)} "
                         "(exact-key-set schema)")
        if row.get("name") != "serve_stage":
            probs.append(f"{at}: 'name' must be 'serve_stage'")
        stage = row.get("stage")
        if stage not in _STAGE_SET:
            probs.append(f"{at}: unknown stage {stage!r} (expected "
                         f"{sorted(_STAGE_SET)})")
        elif stage in seen:
            probs.append(f"{at}: duplicate stage {stage!r}")
        else:
            seen[stage] = row
        if "count" in row and not (_is_count(row["count"])
                                   and row["count"] > 0):
            probs.append(f"{at}: 'count' must be a positive int — a "
                         "stage that never ran proves nothing")
        for k in ("value", "p50_s", "p95_s", "p99_s", "sum_s"):
            if k in row and not (_finite_num(row[k]) and row[k] >= 0):
                probs.append(f"{at}: '{k}' must be a finite non-negative"
                             f" number, got {row[k]!r}")
        if "frac_round" in row and not (
                _finite_num(row["frac_round"])
                and 0.0 <= row["frac_round"] <= 1.0001):
            probs.append(f"{at}: 'frac_round' must be within [0, 1], "
                         f"got {row['frac_round']!r}")
        if row.get("unit") != "s":
            probs.append(f"{at}: 'unit' must be 's'")
        if "quick" in row and not isinstance(row["quick"], bool):
            probs.append(f"{at}: 'quick' must be a bool")
    missing_stages = _STAGE_SET - set(seen)
    if missing_stages:
        probs.append(f"{where}: missing stage row(s) "
                     f"{sorted(missing_stages)} — the breakdown owes "
                     "the full stage set")
    rnd = seen.get("round")
    if rnd is not None and _finite_num(rnd.get("sum_s")):
        child = sum(r["sum_s"] for s, r in seen.items()
                    if s != "round" and _finite_num(r.get("sum_s")))
        if child > rnd["sum_s"] * 1.001:
            probs.append(
                f"{where}: child stages sum ({child:.6f}s) exceeds the "
                f"round wall ({rnd['sum_s']:.6f}s) — mis-nested spans")
    if not any(r.get("quick") for r in seen.values()):
        host = sum(seen[s]["frac_round"] for s in _HOST_STAGES
                   if s in seen and _finite_num(
                       seen[s].get("frac_round")))
        if host >= _HOST_FRAC_BAR:
            probs.append(
                f"{where}: host stages (pack+stack+unpack) at "
                f"{host:.1%} of the round — the committed breakdown "
                f"must stay below {_HOST_FRAC_BAR:.0%} (the PR-11 "
                "device-bound-round acceptance bar)")
    return probs


def check_serve_soak(obj, where: str) -> list[str]:
    """Validate the serve_soak summary object (exact key set, counts,
    percentile keys, NaN/Inf rejection, promise reconciliation)."""
    if not isinstance(obj, dict):
        return [f"{where}: not a JSON object"]
    probs = []
    missing, unknown = _SOAK_KEYS - set(obj), set(obj) - _SOAK_KEYS
    if missing:
        probs.append(f"{where}: missing keys {sorted(missing)}")
    if unknown:
        probs.append(f"{where}: unknown keys {sorted(unknown)} "
                     "(exact-key-set schema)")
    if obj.get("name") != "serve_soak":
        probs.append(f"{where}: 'name' must be 'serve_soak'")
    for k in _SOAK_COUNTS:
        if k in obj and not _is_count(obj[k]):
            probs.append(f"{where}: '{k}' must be a non-negative int, "
                         f"got {obj[k]!r}")
    if all(_is_count(obj.get(k)) for k in
           ("accepted", "completed", "timed_out", "failed",
            "silent_losses")):
        total = (obj["completed"] + obj["timed_out"] + obj["failed"]
                 + obj["silent_losses"])
        if total != obj["accepted"]:
            probs.append(
                f"{where}: accepted ({obj['accepted']}) != completed + "
                f"timed_out + failed + silent_losses ({total}) — the "
                "terminal ledger must reconcile")
    for k in ("resume_bit_identical", "quick"):
        if k in obj and not isinstance(obj[k], bool):
            probs.append(f"{where}: '{k}' must be a bool")
    lat = obj.get("latency_s")
    if lat is not None:
        if not isinstance(lat, dict):
            probs.append(f"{where}: 'latency_s' must be an object")
        else:
            miss = set(_SOAK_PCTS) - set(lat)
            unk = set(lat) - set(_SOAK_PCTS)
            if miss:
                probs.append(f"{where}: latency_s missing {sorted(miss)}")
            if unk:
                probs.append(f"{where}: latency_s unknown keys "
                             f"{sorted(unk)}")
            vals = [lat[k] for k in _SOAK_PCTS if k in lat]
            for k in _SOAK_PCTS:
                v = lat.get(k)
                if v is None:
                    continue
                if isinstance(v, bool) \
                        or not isinstance(v, (int, float)) \
                        or not math.isfinite(v) or v < 0:
                    probs.append(f"{where}: latency_s.{k} must be a "
                                 f"finite non-negative number, got {v!r}")
            if len(vals) == 3 and all(
                    isinstance(v, (int, float)) and not isinstance(v, bool)
                    and math.isfinite(v) for v in vals) \
                    and not (vals[0] <= vals[1] <= vals[2]):
                probs.append(f"{where}: percentiles must be "
                             f"non-decreasing (p50 <= p95 <= p99), got "
                             f"{vals}")
    if "wall_s" in obj:
        w = obj["wall_s"]
        if isinstance(w, bool) or not isinstance(w, (int, float)) \
                or not math.isfinite(w) or w < 0:
            probs.append(f"{where}: 'wall_s' must be a finite "
                         f"non-negative number, got {w!r}")
    if "n" in obj and not (_is_count(obj["n"]) and obj["n"] > 0):
        probs.append(f"{where}: 'n' must be a positive int")
    return probs

def check_serve_multiworker_soak(obj, where: str) -> list[str]:
    """Validate the serve_multiworker_soak summary (exact key set,
    reconciling counts, AND the acceptance bars: >= 3 workers, >= 1
    worker kill, zero silent losses, >= 1 bit-identical migrated
    resume, fairness preserved on non-quick artifacts)."""
    if not isinstance(obj, dict):
        return [f"{where}: not a JSON object"]
    probs = []
    missing, unknown = _MW_KEYS - set(obj), set(obj) - _MW_KEYS
    if missing:
        probs.append(f"{where}: missing keys {sorted(missing)}")
    if unknown:
        probs.append(f"{where}: unknown keys {sorted(unknown)} "
                     "(exact-key-set schema)")
    if obj.get("name") != "serve_multiworker_soak":
        probs.append(f"{where}: 'name' must be 'serve_multiworker_soak'")
    for k in _MW_COUNTS:
        if k in obj and not _is_count(obj[k]):
            probs.append(f"{where}: '{k}' must be a non-negative int, "
                         f"got {obj[k]!r}")
    if all(_is_count(obj.get(k)) for k in
           ("accepted", "completed", "timed_out", "failed",
            "silent_losses")):
        total = (obj["completed"] + obj["timed_out"] + obj["failed"]
                 + obj["silent_losses"])
        if total != obj["accepted"]:
            probs.append(
                f"{where}: accepted ({obj['accepted']}) != completed + "
                f"timed_out + failed + silent_losses ({total}) — the "
                "terminal ledger must reconcile")
    if _is_count(obj.get("poisoned")) and _is_count(obj.get("failed")) \
            and obj["poisoned"] > obj["failed"]:
        probs.append(f"{where}: poisoned ({obj['poisoned']}) > failed "
                     f"({obj['failed']}) — poisoned is a failure class")
    for k in ("migrated_bit_identical", "fairness_ok", "quick"):
        if k in obj and not isinstance(obj[k], bool):
            probs.append(f"{where}: '{k}' must be a bool")
    if not obj.get("quick"):
        # the committed (non-quick) artifact IS the acceptance evidence
        if _is_count(obj.get("workers")) and obj["workers"] < 3:
            probs.append(f"{where}: committed soak needs >= 3 workers, "
                         f"got {obj['workers']}")
        if _is_count(obj.get("worker_kills")) and obj["worker_kills"] < 1:
            probs.append(f"{where}: committed soak recorded no worker "
                         "kill — it proves nothing")
        if obj.get("silent_losses") not in (0, None):
            probs.append(f"{where}: silent_losses must be 0 "
                         f"(got {obj.get('silent_losses')!r})")
        if _is_count(obj.get("migrated_resumes")) \
                and obj["migrated_resumes"] < 1:
            probs.append(f"{where}: committed soak owes >= 1 migrated "
                         "resume")
        if obj.get("migrated_bit_identical") is False:
            probs.append(f"{where}: migrated resumes were not "
                         "bit-identical — broken promise committed")
        if obj.get("fairness_ok") is False:
            probs.append(f"{where}: fairness_ok=false — a tenant was "
                         "starved during failover")
    lat = obj.get("latency_s")
    if lat is not None:
        if not isinstance(lat, dict):
            probs.append(f"{where}: 'latency_s' must be an object")
        else:
            miss = set(_SOAK_PCTS) - set(lat)
            unk = set(lat) - set(_SOAK_PCTS)
            if miss:
                probs.append(f"{where}: latency_s missing {sorted(miss)}")
            if unk:
                probs.append(f"{where}: latency_s unknown keys "
                             f"{sorted(unk)}")
            for k in _SOAK_PCTS:
                v = lat.get(k)
                if v is not None and not (_finite_num(v) and v >= 0):
                    probs.append(f"{where}: latency_s.{k} must be a "
                                 f"finite non-negative number, got {v!r}")
    if "wall_s" in obj and not (_finite_num(obj["wall_s"])
                                and obj["wall_s"] >= 0):
        probs.append(f"{where}: 'wall_s' must be a finite non-negative "
                     f"number, got {obj['wall_s']!r}")
    if "n" in obj and not (_is_count(obj["n"]) and obj["n"] > 0):
        probs.append(f"{where}: 'n' must be a positive int")
    return probs


# resilience metadata (docs/RESILIENCE.md): optional on any row, but
# when present the values must be well-formed — a malformed degraded
# marker is worse than none (it reads as "not degraded")
_BOOL_FIELDS = ("resume", "degraded")
# an execution-failure record's exact key set (utils.retry
# .ExecutionFailure.to_row); unknown keys are rejected so silent schema
# drift inside the records fails loudly like everywhere else
_FAILURE_REQUIRED = {"stage", "error"}
_FAILURE_ALLOWED = _FAILURE_REQUIRED | {"attempts", "elapsed_s",
                                        "fallback"}


def _check_resilience_fields(row: dict, where: str) -> list[str]:
    probs = []
    for key in _BOOL_FIELDS:
        if key in row and not isinstance(row[key], bool):
            probs.append(f"{where}: '{key}' must be a bool, got "
                         f"{row[key]!r}")
    if "retries" in row:
        r = row["retries"]
        if not isinstance(r, int) or isinstance(r, bool) or r < 0:
            probs.append(f"{where}: 'retries' must be a non-negative "
                         f"int, got {r!r}")
    if "execution_failures" in row:
        recs = row["execution_failures"]
        if not isinstance(recs, list):
            probs.append(f"{where}: 'execution_failures' must be a list")
            return probs
        for j, rec in enumerate(recs):
            at = f"{where} failure[{j}]"
            if not isinstance(rec, dict):
                probs.append(f"{at}: not an object")
                continue
            missing = _FAILURE_REQUIRED - set(rec)
            unknown = set(rec) - _FAILURE_ALLOWED
            if missing:
                probs.append(f"{at}: missing {sorted(missing)}")
            if unknown:
                probs.append(f"{at}: unknown keys {sorted(unknown)} "
                             "(schema: stage, error, attempts, "
                             "elapsed_s, fallback)")
            if "stage" in rec and not isinstance(rec["stage"], str):
                probs.append(f"{at}: 'stage' must be a string")
            if "error" in rec and not isinstance(rec["error"], str):
                probs.append(f"{at}: 'error' must be a string")
    return probs


def _check_row(row: dict, path: Path, lineno: int, strict: bool
               ) -> list[str]:
    probs = []
    where = f"{path.name}:{lineno}"
    if not isinstance(row, dict):
        return [f"{where}: row is not a JSON object"]
    name = row.get("name", row.get("metric"))
    if not isinstance(name, str) or not name:
        probs.append(f"{where}: strict artifact row lacks 'name'" if strict
                     else f"{where}: no usable 'name'/'metric' string")
    elif strict and "name" not in row:
        probs.append(f"{where}: strict artifact row must use 'name' "
                     "(not the legacy 'metric' alias)")
    has_value = isinstance(row.get("value"), (int, float)) \
        and not isinstance(row.get("value"), bool)
    has_error = isinstance(row.get("error"), str)
    if has_value and not math.isfinite(row["value"]):
        # json.loads happily parses NaN/Infinity (non-standard JSON!),
        # and a NaN value silently poisons every trend comparison that
        # touches it (NaN compares false against everything) — reject
        probs.append(f"{where}: non-finite 'value' ({row['value']!r}) — "
                     "record an 'error' string instead")
    elif strict and not (has_value or has_error):
        # strict rows normally carry a numeric value; a per-cell
        # ExecutionFailure (docs/RESILIENCE.md — the suite continued
        # past a failing cell) is the one legal substitute
        probs.append(f"{where}: strict artifact row lacks numeric "
                     "'value' (or a recorded 'error')")
    elif not (has_value or has_error):
        probs.append(f"{where}: neither numeric 'value' nor 'error' string")
    probs.extend(_check_resilience_fields(row, where))
    if "n" in row:
        if not isinstance(row["n"], int) or isinstance(row["n"], bool) \
                or row["n"] <= 0:
            probs.append(f"{where}: 'n' must be a positive int, got "
                         f"{row['n']!r}")
    elif strict:
        probs.append(f"{where}: strict artifact row lacks 'n'")
    return probs


def check_file(path: Path) -> list[str]:
    """Validate one committed artifact; returns a list of problems."""
    text = path.read_text()
    lines = [ln for ln in text.strip().splitlines() if ln.strip()]
    if not lines:
        return [f"{path.name}: empty artifact"]
    # summary-shaped: the whole (multi-line, pretty-printed) file is one
    # JSON object — trials_summary.json and friends; a single line that
    # parses as an object without a name/metric key counts too
    try:
        whole = json.loads(text)
    except json.JSONDecodeError:
        whole = None
    if path.name == SERVE_SOAK:
        if whole is None:
            return [f"{path.name}: unparseable serve-soak artifact"]
        return check_serve_soak(whole, path.name)
    if path.name == SERVE_MW_SOAK:
        if whole is None:
            return [f"{path.name}: unparseable multiworker-soak artifact"]
        return check_serve_multiworker_soak(whole, path.name)
    if path.name == TRACE_SOAK:
        if whole is None:
            return [f"{path.name}: unparseable trace-soak artifact"]
        return check_trace_soak(whole, path.name)
    if path.name == SLO_DETECTION:
        if whole is None:
            return [f"{path.name}: unparseable slo-detection artifact"]
        return check_slo_detection(whole, path.name)
    if path.name in (SERVE_THROUGHPUT, TELEMETRY_OVERHEAD,
                     LOCK_OVERHEAD, SERVE_BREAKDOWN, SCENARIO_SUITE,
                     SERVE_OVERLOAD, ROUTER_FLEET, PIPELINE):
        rows, probs = [], []
        for i, line in enumerate(lines, 1):
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError as e:
                probs.append(f"{path.name}:{i}: unparseable row ({e})")
        checker = {SERVE_THROUGHPUT: check_serve_throughput,
                   TELEMETRY_OVERHEAD: check_telemetry_overhead,
                   LOCK_OVERHEAD: check_lock_overhead,
                   SERVE_BREAKDOWN: check_serve_latency_breakdown,
                   SCENARIO_SUITE: check_scenario_suite,
                   SERVE_OVERLOAD: check_serve_overload,
                   ROUTER_FLEET: check_router_fleet,
                   PIPELINE: check_pipeline_n1000}[
                       path.name]
        return probs + checker(rows, path.name)
    if isinstance(whole, dict) and (
            len(lines) > 1
            or ("name" not in whole and "metric" not in whole)):
        return []
    probs = []
    strict = path.name in STRICT_ROWS
    for i, line in enumerate(lines, 1):
        try:
            row = json.loads(line)
        except json.JSONDecodeError as e:
            probs.append(f"{path.name}:{i}: unparseable row ({e})")
            continue
        probs.extend(_check_row(row, path, i, strict))
    return probs


def check_all(results_dir: Path = RESULTS) -> list[str]:
    probs = []
    files = sorted(results_dir.glob("*.json"))
    if not files:
        return [f"no committed artifacts under {results_dir}"]
    for f in files:
        probs.extend(check_file(f))
    return probs


def main() -> int:
    probs = check_all()
    if probs:
        print(f"ARTIFACT SCHEMA DRIFT ({len(probs)} problem(s)):")
        for p in probs:
            print(f"  {p}")
        return 1
    print(f"all {len(sorted(RESULTS.glob('*.json')))} committed "
          "results/*.json artifacts pass the schema check")
    return 0


if __name__ == "__main__":
    sys.exit(main())
