"""router_fleet — the cross-process p99-vs-offered-load surface for
the swarmrouter tier (ROADMAP open item 1; docs/SERVICE.md §process
mode, docs/SCALING.md §cross-process capacity).

Three kinds of OS process, no shared memory between them:

- the CLIENT fleet: one `serve.traffic` open-loop fleet per level,
  running in its OWN subprocess (``--client-child``) — the p99 it
  reports crossed two real process boundaries;
- the ROUTER: this process hosts `serve.router.SwarmRouter`, the
  stateless wire front door + supervisor;
- the WORKERS: 2 `serve.procworker` processes, each its own jax
  runtime + journal, spawned and leased by the router.

Per level the row reports goodput, client-observed p50/p99, the full
client outcome ledger, and the pid provenance proving the separation
(client pid != router pid != worker pids). The DRILL row runs the
rolling-restart chaos sequence under 1x load: two staggered SIGKILLs
(hard process death mid-flight, in-flight work migrated through the
per-slot journals), then a graceful drain -> fence -> respawn ->
re-admit pass per slot, a bit-identical probe (a fixed-seed rollout
killed mid-run must resume to the SAME digest an uncontended run
produces), and the fleet-journal audit: `postmortem.fleet_reconstruct`
across every slot journal must attribute every accepted request with
ZERO losses.

Acceptance bars, enforced AS SCHEMA by
`benchmarks/check_results.py::check_router_fleet`:

- >= 3 committed offered-load levels + exactly one drill row;
- client/router/worker pids pairwise distinct on every row;
- drill: kills >= 2, migrations >= 1, detection < 2000 ms,
  ``journaled_losses == 0``, ``bit_identical`` true.

Run:

    JAX_PLATFORMS=cpu python benchmarks/router_fleet.py [--quick] \
        [--out benchmarks/results/router_fleet.json]
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

RESULTS = Path(__file__).resolve().parent / "results"

MULTIPLIERS = (0.5, 1.0, 2.0, 4.0)
MULTIPLIERS_QUICK = (0.5, 2.0)
DURATION_S = 6.0
DURATION_S_QUICK = 2.5
N = 5
SLOTS = 2

# each worker cell: the serve_overload single-process posture (modest
# bounded queues, 4-slot batches) so the cross-process capacity is
# comparable to the committed single-process ~7.5 req/s anchor
WORKER_SERVICE = dict(max_batch=4, quantum_chunks=4,
                      max_queue_per_tenant=16, max_queue_total=48,
                      idle_poll_s=0.01)
# pre-READY warm per worker, in PACKING GROUPS: each group is
# co-submitted so the scheduler forms one batch of exactly that size —
# rollout batches 4, 3, 2, 1 and assign batches 2, 1 are every
# composition traffic can reach. (One big warm burst only compiles the
# sizes it happens to pack into; the first mid-run batch of an
# uncovered size then stalls the whole queue behind a ~5 s compile —
# measured as a cliff where every queued request resolves at once.)
def _warm_rolls(count: int, base: int) -> list:
    return [["rollout", {"n": N, "ticks": 60, "chunk_ticks": 20,
                         "seed": base + i}] for i in range(count)]


WARM_GROUPS = ([_warm_rolls(k, 900 + 10 * k) for k in (4, 3, 2, 1)]
               + [[["assign", {"n": N, "seed": s}] for s in (1, 2)],
                  [["assign", {"n": N, "seed": 3}]]])

# the traffic mix: two placement buckets (the rollout shape bucket and
# the assign single bucket) so BOTH worker processes carry load —
# rendezvous placement is per-bucket, not per-request
MIX = (("rollout", 0.6), ("assign", 0.4))

PROBE = {"n": N, "ticks": 60, "chunk_ticks": 20, "seed": 424242}


# --------------------------------------------------------------- child

def run_client_child(args) -> int:
    """The client fleet, in its own process: run one open-loop
    `TrafficFleet` against the router's TCP front door and print the
    ledger as the last stdout line. The parent never constructs a
    client — the p99 in the artifact is measured from OUTSIDE the
    router's process."""
    from aclswarm_tpu.serve.traffic import TrafficConfig, TrafficFleet

    host, port = args.tcp.rsplit(":", 1)
    cfg = TrafficConfig(
        seed=args.seed, duration_s=args.duration,
        offered_hz=args.offered_hz, mix=MIX, n=N,
        reject_retries=args.reject_retries, max_retry_wait_s=8.0,
        slowloris_clients=0, corrupt_clients=0,
        reconnect_storms=args.storms,
        storm_period_s=max(1.0, args.duration / 3.0),
        drain_timeout_s=300.0)
    rep = TrafficFleet(cfg, host, int(port)).run()
    print("CLIENT_REPORT " + json.dumps(
        {"pid": os.getpid(), "report": rep}), flush=True)
    return 0


def _spawn_client(tcp: tuple, offered_hz: float, duration_s: float,
                  seed: int, storms: int = 0,
                  reject_retries: int = 2) -> subprocess.Popen:
    return subprocess.Popen(
        [sys.executable, str(Path(__file__).resolve()),
         "--client-child", "--tcp", f"{tcp[0]}:{tcp[1]}",
         "--offered-hz", f"{offered_hz}", "--duration",
         f"{duration_s}", "--seed", str(seed), "--storms", str(storms),
         "--reject-retries", str(reject_retries)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})


def _client_report(proc: subprocess.Popen, timeout_s: float) -> dict:
    out, _ = proc.communicate(timeout=timeout_s)
    for line in reversed(out.splitlines()):
        if line.startswith("CLIENT_REPORT "):
            return json.loads(line[len("CLIENT_REPORT "):])
    raise RuntimeError(f"client child exited {proc.returncode} without "
                       f"a report:\n{out[-2000:]}")


# -------------------------------------------------------------- parent

def _fleet(journal_root: str):
    from aclswarm_tpu.serve.router import RouterConfig, SwarmRouter

    router = SwarmRouter(RouterConfig(
        journal_root=journal_root, slots=SLOTS,
        spawn_timeout_s=420.0, drain_timeout_s=120.0,
        # admission cap = the fleet's true queue capacity: overload is
        # shed at the front door with a K_REJECT + retry hint instead
        # of soaking a backlog whose only future is a slow queue_full
        max_inflight=SLOTS * int(WORKER_SERVICE["max_queue_total"]),
        worker={"service": WORKER_SERVICE,
                "warm_groups": WARM_GROUPS}))
    router.start()
    if not router.wait_ready(420.0):
        router.close()
        raise RuntimeError(f"worker fleet never came up: "
                           f"{router.fleet()}")
    return router


def _pids(router) -> dict:
    return {"router_pid": os.getpid(),
            "worker_pids": sorted(f["pid"] for f in router.fleet()
                                  if f["pid"] is not None)}


def _run_level(router, offered_hz: float, duration_s: float,
               seed: int, storms: int = 0,
               reject_retries: int = 2) -> dict:
    t0 = time.perf_counter()
    child = _spawn_client(router.tcp_address, offered_hz, duration_s,
                          seed, storms, reject_retries)
    got = _client_report(child, duration_s + 360.0)
    rep = got["report"]
    rep.update(offered_hz=offered_hz, client_pid=got["pid"],
               level_wall_s=time.perf_counter() - t0)
    return rep


def calibrate(router, duration_s: float = 4.0) -> float:
    """Measured fleet capacity: drain rate under polite saturation
    (no hint-honoring retries — the retry tail would stretch the wall
    and undersell it), from a separate client process like every
    level. Saturation is ~20x the fleet drain rate, NOT the 1200 Hz
    the single-process bench uses: past the point where every queue
    is pinned full, extra offered load only adds reject-frame chew
    time to the wall and the 'capacity' would measure the codec front
    door, not the fleet."""
    rep = _run_level(router, 120.0, duration_s, seed=99,
                     reject_retries=0)
    cap = rep["completed"] / rep["wall_s"]
    print(f"calibrated fleet capacity: {cap:.1f} req/s "
          f"({rep['completed']} completed / {rep['wall_s']:.1f} s, "
          f"{SLOTS} worker processes)", flush=True)
    return cap


def _row(rep: dict, mult: float, capacity_hz: float, backend: str,
         prov: dict, quick: bool) -> dict:
    goodput = (rep["completed"] / rep["wall_s"]) if rep["wall_s"] \
        else 0.0
    pids = [rep["client_pid"], prov["router_pid"],
            *prov["worker_pids"]]
    return {
        "name": "router_fleet",
        "level": f"{mult:g}x",
        "multiplier": mult,
        "n": N,
        "backend": backend,
        "workers": SLOTS,
        "capacity_hz": round(capacity_hz, 3),
        "offered_hz": round(rep["offered_hz"], 3),
        "value": round(goodput, 3),
        "unit": "Hz",
        "p50_s": round(rep["latency_p50_s"], 4),
        "p99_s": round(rep["latency_p99_s"], 4),
        "offered": rep["offered"],
        "completed": rep["completed"],
        "timed_out": rep["timed_out"],
        "shed": rep["rejected_final"],
        "cancelled": rep["cancelled"],
        "wire_lost": rep["wire_lost"],
        "failed_other": rep["failed_other"],
        "unresolved": rep["unresolved"],
        "retry_submits": rep["retry_submits"],
        "client_pid": rep["client_pid"],
        "router_pid": prov["router_pid"],
        "worker_pids": prov["worker_pids"],
        "separate_client_process": len(set(pids)) == len(pids),
        "wall_s": round(rep["wall_s"], 2),
        "quick": quick,
    }


def _busiest_slot(router, timeout_s: float,
                  prefer_rid: str = "") -> int:
    """Block until SOME live slot is carrying in-flight work (the
    client child pays a jax-import startup tax before its first
    arrival, so 'wait until traffic flows' needs a real timeout) and
    return that slot — a SIGKILL that lands on an idle process proves
    nothing about migration. With ``prefer_rid``, aim at the process
    carrying that request so the kill provably lands mid-flight."""
    from aclswarm_tpu.serve.router import UP

    t_end = time.monotonic() + timeout_s
    pick = 0
    while time.monotonic() < t_end:
        if prefer_rid:
            uid = router.route_uid(prefer_rid)
            if uid and router.inflight_on(uid) > 0:
                return int(uid.split(".")[0])
        loads = {f["slot"]: router.inflight_on(f["uid"])
                 for f in router.fleet() if f["state"] == UP}
        if loads:
            pick = max(loads, key=lambda s: loads[s])
            if loads[pick] > 0:
                return pick
        time.sleep(0.02)
    return pick


def _run_drill(router, capacity_hz: float, backend: str,
               duration_s: float, seed: int, quick: bool) -> dict:
    """The rolling-restart drill under 1x load: staggered SIGKILL of
    every slot mid-traffic (hard failover, work migrated through the
    journals), a bit-identical probe, then the graceful
    drain->fence->respawn->re-admit pass."""
    from aclswarm_tpu.serve import ServiceConfig, SwarmService
    from aclswarm_tpu.serve.wire import WireClient

    # the bit-parity oracle, computed in-parent: deterministic rollout
    ref = SwarmService(ServiceConfig(max_batch=1))
    want = ref.submit("rollout", PROBE).result(600)
    ref.close()
    assert want.ok

    prov = _pids(router)
    t0 = time.perf_counter()
    # a longer window than the levels: both staggered kills plus the
    # respawn gap must land inside live traffic
    drill_dur = max(duration_s * 2.0, 10.0)
    child = _spawn_client(router.tcp_address, capacity_hz, drill_dur,
                          seed, storms=1)
    # hold until the child's open loop is actually offering (its jax
    # import + fleet construction precede the first arrival)
    _busiest_slot(router, 120.0)
    # the probe rides the same front door from THIS process's client,
    # submitted only once traffic queues exist for it to sit behind —
    # the first kill aims at ITS slot, so the bit-parity check
    # exercises the migrated-resume path, not an uncontended run
    probe_client = WireClient(tcp=router.tcp_address,
                              client_id="drill-probe", tenant="probe")
    probe = probe_client.submit("rollout", PROBE,
                                request_id="drill-probe-roll")
    failovers_pre = router.telemetry.counter(
        "router_failovers_total").value
    kills = []
    for n_kill in range(SLOTS):
        victim = _busiest_slot(
            router, 30.0,
            prefer_rid="drill-probe-roll" if n_kill == 0 else "")
        kills.append(router.kill_slot(victim))
        time.sleep(max(0.5, duration_s / 4.0))
    probe_res = probe.result(timeout=600)
    # migrations = every route requeued onto a survivor because its
    # process died under it, whichever of the router's three requeue
    # paths caught it (declare-dead bulk, the dispatch-vs-death safety
    # net, or a worker-loss terminal) — the death-ledger `migrated`
    # field alone undercounts when the data-plane error outraces the
    # supervision-channel death
    migrations = (router.telemetry.counter(
        "router_failovers_total").value - failovers_pre)
    got = _client_report(child, duration_s + 360.0)
    rep = got["report"]
    restart = router.rolling_restart()
    probe_client.close()

    detect = [k["detect_s"] for k in kills
              if k["detect_s"] is not None]
    return {
        "name": "router_fleet",
        "level": "drill",
        "multiplier": 1.0,
        "n": N,
        "backend": backend,
        "workers": SLOTS,
        "capacity_hz": round(capacity_hz, 3),
        "offered_hz": round(capacity_hz, 3),
        "value": len(kills),
        "unit": "kills",
        "kills": len(kills),
        "migrations": int(migrations),
        "detection_ms_max": round(max(detect) * 1e3, 1) if detect
        else None,
        "readmitted": all(k["readmitted"] for k in kills),
        "restarts": len(restart),
        "restart_drained": all(r["drained"] for r in restart),
        "restart_readmitted": all(r["readmitted"] for r in restart),
        "bit_identical": bool(
            probe_res.ok
            and probe_res.value["digest"] == want.value["digest"]),
        "probe_status": probe_res.status,
        "probe_failovers": probe_res.failovers,
        "offered": rep["offered"],
        "completed": rep["completed"],
        "timed_out": rep["timed_out"],
        "shed": rep["rejected_final"],
        "cancelled": rep["cancelled"],
        "wire_lost": rep["wire_lost"],
        "failed_other": rep["failed_other"],
        "unresolved": rep["unresolved"],
        "client_pid": got["pid"],
        "router_pid": prov["router_pid"],
        "worker_pids": prov["worker_pids"],
        "separate_client_process": got["pid"] != os.getpid(),
        # the journal audit lands after the fleet closes (main fills
        # these in — the journals must be quiescent to be the whole
        # story)
        "journaled_losses": None,
        "duplicate_terminals": None,
        "pm_resolved": None,
        "pm_gap_free": None,
        "wall_s": round(time.perf_counter() - t0, 2),
        "quick": quick,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true",
                    help="2 short levels + drill (CI smoke; artifact "
                         "not committed)")
    ap.add_argument("--seed", type=int, default=30)
    ap.add_argument("--out", default=None,
                    help="artifact path ('' to skip; default: the "
                         "committed artifact for full runs, NO write "
                         "for --quick)")
    ap.add_argument("--client-child", action="store_true",
                    help="(internal) run the traffic fleet in this "
                         "process and print its ledger")
    ap.add_argument("--tcp", default=None)
    ap.add_argument("--offered-hz", type=float, default=10.0)
    ap.add_argument("--duration", type=float, default=6.0)
    ap.add_argument("--storms", type=int, default=0)
    ap.add_argument("--reject-retries", type=int, default=2)
    args = ap.parse_args(argv)
    if args.client_child:
        return run_client_child(args)
    if args.out is None:
        args.out = "" if args.quick \
            else str(RESULTS / "router_fleet.json")

    import jax
    backend = jax.default_backend()
    mults = MULTIPLIERS_QUICK if args.quick else MULTIPLIERS
    dur = DURATION_S_QUICK if args.quick else DURATION_S

    with tempfile.TemporaryDirectory(
            prefix="aclswarm_router_fleet_") as root:
        router = _fleet(root)
        try:
            cap = calibrate(router, 2.5 if args.quick else 4.0)
            prov = _pids(router)
            rows = []
            for k, mult in enumerate(mults):
                rep = _run_level(router, mult * cap, dur,
                                 seed=args.seed + k)
                row = _row(rep, mult, cap, backend, prov,
                           bool(args.quick))
                rows.append(row)
                print(json.dumps(row), flush=True)
            drill = _run_drill(router, cap, backend, dur,
                               seed=args.seed + 50,
                               quick=bool(args.quick))
            jdirs = [str(p) for p in router.journal_dirs()]
        finally:
            router.close()

        # the fleet is dead; the journals are the whole story now
        from aclswarm_tpu.telemetry import postmortem
        fleet_pm = postmortem.fleet_reconstruct(jdirs)
        drill.update(
            journaled_losses=len(fleet_pm["losses"]),
            duplicate_terminals=len(fleet_pm["duplicate_terminals"]),
            pm_resolved=fleet_pm["resolved"],
            pm_gap_free=fleet_pm["gap_free"])
        rows.append(drill)
        print(json.dumps(drill), flush=True)

    bad = []
    if fleet_pm["losses"]:
        bad.append(f"journaled losses: {fleet_pm['losses'][:8]}")
    if not drill["bit_identical"]:
        bad.append(f"probe not bit-identical "
                   f"(status {drill['probe_status']})")
    if sum(r["unresolved"] for r in rows):
        bad.append("client-side unresolved tickets")
    if bad:
        print("FAIL: " + "; ".join(bad))
        return 1
    if args.out:
        p = Path(args.out)
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
        print(f"wrote {p}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
