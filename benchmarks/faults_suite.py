"""Fault-recovery sweep: measured elastic-swarm evidence.

The tentpole demonstration of `aclswarm_tpu.faults`: ONE compiled
batched rollout in which every trial carries a DIFFERENT fault script —
a (dropout fraction x link-loss rate) grid plus a no-fault control row —
runs under `vmap` with the shared-tick decimation intact, on the
fully-faithful decentralized stack (CBAA consensus auctions over flooded
localization estimates, the mode where BOTH fault axes bite: dropouts
shrink the auction and the comm graph, link loss starves the flood and
the consensus rounds).

Per trial the swarm converges to a random rigid formation, a scripted
fraction of the fleet drops mid-flight (tick D), and the survivors'
masked re-auction + control recover formation; the dropped vehicles
rejoin at tick R and the fleet re-absorbs them. The on-device recovery
clock (`sim.summary`) emits time-to-reconvergence and assignment churn
for both events; this driver commits them as

    benchmarks/results/fault_recovery.json      {name, n, value} rows
                                                (strict schema —
                                                benchmarks/check_results)

Run:
    python benchmarks/faults_suite.py [--quick] [--n 10] [--out PATH]
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

RESULTS = Path(__file__).resolve().parent / "results"

# the sweep grid: every (dropout_frac, link_loss) cell is one trial row
# of the SAME batched rollout; (0, 0) is the no-fault control row
GRID = [(0.0, 0.0), (0.1, 0.0), (0.3, 0.0), (0.5, 0.0),
        (0.1, 0.3), (0.3, 0.3), (0.1, 0.6), (0.3, 0.6)]

# per-scale problem shaping (generation box per trials_suite conventions;
# spacing >= 2 * d_avoid_thresh so parked vehicles sit outside each
# other's avoidance shells — docs/SCALE_TUNING.md §5) and fault timeline
# (the recovery windows must clear the scale's own convergence
# transient: n=100 under reference-default control at the 40 m box
# converges in ~2600 ticks — measured baseline_conv_tick_n100 — so its
# drop/rejoin events and windows stretch accordingly)
SCALES = {
    10: dict(box=(15.0, 15.0, 2.0), min_dist=2.0,
             drop_tick=300, rejoin_tick=1500, n_ticks=2640),
    100: dict(box=(40.0, 40.0, 3.0), min_dist=3.0,
              drop_tick=600, rejoin_tick=4200, n_ticks=7800),
}


def _problem(n: int, seed: int):
    """One seeded formation + an airborne start displaced a few metres
    from it. The displacement matters: the dropout is scripted
    MID-TRANSIT, so the drop-recovery window measures the survivors
    finishing convergence with the dead frozen mid-air (masked out of
    graph and avoidance), and the rejoin-recovery window measures the
    fleet re-absorbing vehicles that froze ~3 m off their points."""
    import jax.numpy as jnp

    from aclswarm_tpu import gains as gainslib
    from aclswarm_tpu.core.types import make_formation
    from aclswarm_tpu.harness import formgen

    box = SCALES[n]["box"]
    spec = formgen.generate_specs(
        n, seed=seed, l=box[0], w=box[1], h=box[2],
        min_dist=SCALES[n]["min_dist"], k=1)[0]
    g = np.asarray(gainslib.solve_gains(spec.points, spec.adjmat,
                                        max_nonedges=max(n - 4, 1)))
    form = make_formation(jnp.asarray(spec.points), jnp.asarray(spec.adjmat),
                          jnp.asarray(g))
    rng = np.random.default_rng(seed)
    q0 = np.asarray(spec.points).copy()
    q0[:, :2] += rng.normal(size=(n, 2)) * 3.0   # a few metres of transit
    q0[:, 2] = np.abs(q0[:, 2]) + 2.0 \
        + rng.normal(size=n) * 0.3               # airborne, above the floor
    return form, q0


def run_scale(n: int, *, seed: int = 1, drop_tick: int | None = None,
              rejoin_tick: int | None = None, n_ticks: int | None = None,
              chunk: int = 120, assign_every: int = 60,
              check_mode: str = "off",
              checkpoint_dir: str | None = None,
              resume: bool = True) -> list[dict]:
    import jax
    import jax.numpy as jnp

    from aclswarm_tpu import faults, sim
    from aclswarm_tpu.core.types import ControlGains, SafetyParams
    from aclswarm_tpu.resilience import (ChunkExecutor, checkpoint as
                                         ckptlib, maybe_crash)
    from aclswarm_tpu.sim import summary as sumlib
    from aclswarm_tpu.utils import get_logger

    assert chunk % assign_every == 0, "shared auction phase"
    drop_tick = SCALES[n]["drop_tick"] if drop_tick is None else drop_tick
    rejoin_tick = SCALES[n]["rejoin_tick"] if rejoin_tick is None \
        else rejoin_tick
    n_ticks = SCALES[n]["n_ticks"] if n_ticks is None else n_ticks
    form, q0 = _problem(n, seed)
    B = len(GRID)
    dtype = jnp.asarray(q0).dtype
    scheds = [faults.sample_schedule(seed * 1000 + i, n, dropout_frac=df,
                                     drop_tick=drop_tick,
                                     rejoin_tick=rejoin_tick,
                                     link_loss=pl, dtype=dtype)
              for i, (df, pl) in enumerate(GRID)]
    states = [sim.init_state(q0, localization=True, faults=sc,
                             checks=check_mode == "on")
              for sc in scheds]
    bstate = jax.tree.map(lambda *xs: jnp.stack(xs), *states)
    bform = jax.tree.map(lambda *xs: jnp.stack(xs), *([form] * B))
    sparams = SafetyParams(
        bounds_min=jnp.asarray([-100.0, -100.0, 0.0]),
        bounds_max=jnp.asarray([100.0, 100.0, 30.0]))
    cfg = sim.SimConfig(assignment="cbaa", assign_every=assign_every,
                        localization="flooded",
                        colavoid_neighbors=16 if n > 16 else None,
                        check_mode=check_mode)
    window = 100                              # 1 s at the 100 Hz tick
    carry = sumlib.init_carry(n, window, dtype=dtype, batch=B)

    t0 = time.time()
    conv = np.zeros((B, 0), bool)
    rec = np.zeros((B, 0), np.int32)
    chn = np.zeros((B, 0), np.int32)
    nal = np.zeros((B, 0), np.int32)

    # --- resilience (docs/RESILIENCE.md): mid-rollout checkpoint/resume
    # + retried/degraded chunk launches. The sweep carry is (bstate,
    # carry) plus the accumulated observable arrays.
    execu = ChunkExecutor(log=get_logger("faults_suite"))
    stem = f"faults_n{n}_seed{seed}"
    cfg_hash = ckptlib.config_hash(dict(
        n=n, seed=seed, drop_tick=drop_tick, rejoin_tick=rejoin_tick,
        n_ticks=n_ticks, chunk=chunk, assign_every=assign_every,
        check_mode=check_mode, grid=GRID))
    c0_start = 0
    resumed = False
    if checkpoint_dir is not None and resume:
        path = ckptlib.latest_checkpoint(checkpoint_dir, stem)
        if path is not None:
            payload, man = ckptlib.load_checkpoint(
                path, expected=ckptlib.expected_manifest(
                    "faults_suite", cfg_hash))
            bstate = ckptlib.restore_tree(bstate, payload["state"],
                                          path=path, what="SimState")
            carry = ckptlib.restore_tree(carry, payload["carry"],
                                         path=path, what="SummaryCarry")
            conv = np.asarray(payload["conv"], bool)
            rec = np.asarray(payload["rec"], np.int32)
            chn = np.asarray(payload["chn"], np.int32)
            nal = np.asarray(payload["nal"], np.int32)
            c0_start = int(man["c0_next"])
            resumed = True

    for c0 in range(c0_start, n_ticks, chunk):
        bstate, carry, summ = execu.run(
            lambda: sumlib.batched_rollout_summary(
                bstate, carry, bform, ControlGains(), sparams, cfg,
                chunk, None, 0, window=window, takeoff_alt=2.0),
            stage=f"faults_n{n}:c{c0}")
        if check_mode == "on":
            # sanitized run: the swarmcheck codes ride the arrays this
            # loop already syncs; a violation aborts the sweep with
            # (trial row, tick, contract) attribution
            from aclswarm_tpu.analysis import invariants as invlib
            codes = np.asarray(summ.inv_code)
            for b in range(B):
                invlib.raise_on_violation(codes[b], trial=b, tick0=c0)
        conv = np.concatenate([conv, np.asarray(summ.conv_all)], axis=1)
        rec = np.concatenate([rec, np.asarray(summ.recovery_ticks)], axis=1)
        chn = np.concatenate([chn, np.asarray(summ.fault_churn)], axis=1)
        nal = np.concatenate([nal, np.asarray(summ.n_alive)], axis=1)
        if checkpoint_dir is not None and c0 + chunk < n_ticks:
            ckptlib.write_checkpoint(
                checkpoint_dir, stem,
                {"state": ckptlib.tree_arrays(bstate),
                 "carry": ckptlib.tree_arrays(carry),
                 "conv": conv, "rec": rec, "chn": chn, "nal": nal},
                ckptlib.make_manifest("faults_suite", cfg_hash,
                                      chunk=(c0 + chunk) // chunk,
                                      c0_next=c0 + chunk))
        maybe_crash("suite", (c0 + chunk) // chunk)
    if checkpoint_dir is not None:
        ckptlib.clear_checkpoints(checkpoint_dir, stem)
    wall = time.time() - t0

    def first_recovery(b, after, before):
        done = np.nonzero(rec[b, after:before] >= 0)[0]
        if done.size == 0:
            return -1, -1
        t = after + int(done[0])
        return int(rec[b, t]), int(chn[b, t])

    rows = []
    base = dict(n=n, unit="ticks", batch=B, seed=seed,
                drop_tick=drop_tick, rejoin_tick=rejoin_tick,
                assignment="cbaa", localization="flooded",
                wall_s=round(wall, 1))
    if resumed:
        # wall_s covers only the post-resume tail then — mark it
        base["resume"] = True
    base.update(execu.row_fields())
    for b, (df, pl) in enumerate(GRID):
        tag = f"n{n}_drop{int(df * 100):02d}_loss{int(pl * 100):02d}"
        if df == 0.0 and pl == 0.0:
            # control row: no fault events; record the initial
            # convergence tick as the baseline transient (first
            # full-window tick whose predicate holds — earlier ticks
            # average the zero-padded history, which the host FSM's
            # push counters would gate)
            c = np.nonzero(conv[b, window:])[0]
            rows.append(dict(base, name=f"baseline_conv_tick_n{n}",
                             value=int(c[0]) + window if c.size else -1,
                             dropout_frac=df, link_loss=pl))
            continue
        for event, lo, hi in (("drop", drop_tick, rejoin_tick),
                              ("rejoin", rejoin_tick, n_ticks)):
            r, c = first_recovery(b, lo, hi)
            rows.append(dict(
                base, name=f"recovery_ticks_{tag}_{event}", value=r,
                dropout_frac=df, link_loss=pl, event=event,
                churn=c, recovered=r >= 0,
                n_alive_during=int(nal[b, lo + 1])))
    return rows


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true",
                    help="n=10 only, short horizon (smoke)")
    ap.add_argument("--n", type=int, action="append", default=None,
                    help="scale(s) to run (default 10 and 100)")
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--out", default=str(RESULTS / "fault_recovery.json"))
    ap.add_argument("--check-mode", choices=("off", "on"), default="off",
                    help="run the sweep with the swarmcheck sanitizer "
                    "compiled in (aclswarm_tpu.analysis.invariants): a "
                    "contract violation aborts with trial/tick/contract "
                    "attribution instead of poisoning the artifact")
    ap.add_argument("--checkpoint-dir", default=None,
                    help="chunk-boundary checkpoints: a killed sweep "
                    "resumes mid-rollout AND mid-grid from here "
                    "(docs/RESILIENCE.md)")
    ap.add_argument("--no-resume", action="store_true",
                    help="ignore existing checkpoints (fresh run)")
    args = ap.parse_args(argv)

    import jax
    ns = args.n or ([10] if args.quick else [10, 100])
    kw = dict(drop_tick=300, rejoin_tick=720, n_ticks=1200) if args.quick \
        else {}
    all_rows = []
    failed_cells = []
    from aclswarm_tpu.resilience import InjectedCrash
    from aclswarm_tpu.utils.retry import ExecutionFailure
    for n in ns:
        print(f"=== fault sweep n={n} (B={len(GRID)}) ===", flush=True)
        t0 = time.time()
        try:
            rows = run_scale(n, seed=args.seed, check_mode=args.check_mode,
                             checkpoint_dir=args.checkpoint_dir,
                             resume=not args.no_resume, **kw)
        except InjectedCrash:
            raise          # scripted preemption: die as scripted
        except Exception as e:      # noqa: BLE001 — recorded, not hidden
            # one failing scale must not lose the rest of the grid: the
            # cell's failure becomes a structured artifact row and the
            # sweep continues (the exit code still fails at the end)
            failed_cells.append(f"n={n}: {e}")
            fail = ExecutionFailure(stage=f"fault_sweep_n{n}",
                                    error=f"{type(e).__name__}: {e}",
                                    elapsed_s=time.time() - t0)
            all_rows.append(dict(name=f"fault_sweep_n{n}", n=n,
                                 error=fail.error, seed=args.seed,
                                 execution_failures=[fail.to_row()]))
            print(f"FAILED n={n}: {e} — continuing the sweep", flush=True)
            continue
        for r in rows:
            r["device"] = jax.default_backend()
            print(json.dumps(r), flush=True)
        all_rows.extend(rows)

    RESULTS.mkdir(exist_ok=True)
    out = Path(args.out)
    with out.open("w") as f:
        for r in all_rows:
            f.write(json.dumps(r) + "\n")
    print(f"wrote {out} ({len(all_rows)} rows)")

    # self-check against the committed-artifact schema guard
    from check_results import check_file
    probs = check_file(out)
    if probs:
        print("SCHEMA DRIFT in freshly written artifact:")
        for p in probs:
            print(f"  {p}")
        return 1
    if failed_cells:
        print(f"{len(failed_cells)} grid cell(s) FAILED "
              "(recorded as error rows):")
        for c in failed_cells:
            print(f"  {c}")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
