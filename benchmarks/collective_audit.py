"""Collective inventory of the sharded step: what GSPMD actually inserts.

Round-2 review: "shard the agent axis" had correctness evidence but no
communication story. This tool compiles the sharded kernels on a virtual
8-device mesh (identical partitioning decisions to a real v5e-8 — GSPMD
partitions by sharding annotations, not by backend), walks the optimized
HLO, and inventories every collective op with its payload bytes. Output:

    benchmarks/results/collective_audit.json

plus a human-readable table on stdout. The per-tick byte totals against
v5e ICI bandwidth (~400 GB/s/link bidirectional) give the expected
multi-chip scaling; see docs/SCALING.md for the analysis.

Run:  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
          python benchmarks/collective_audit.py [--n 1000]
"""
from __future__ import annotations

import argparse
import json
import re
import sys
from collections import defaultdict
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

RESULTS = Path(__file__).resolve().parent / "results"

COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter",
               "collective-permute", "all-to-all")

_SHAPE = re.compile(r"(f64|f32|bf16|f16|s32|u32|s8|u8|pred)\[([\d,]*)\]")
_BYTES = {"f64": 8, "f32": 4, "bf16": 2, "f16": 2, "s32": 4, "u32": 4,
          "s8": 1, "u8": 1, "pred": 1}


def _op_bytes(line: str) -> int:
    """Output payload bytes of one HLO op line (first shape on the line)."""
    m = _SHAPE.search(line)
    if not m:
        return 0
    dtype, dims = m.groups()
    count = int(np.prod([int(d) for d in dims.split(",") if d])) if dims \
        else 1
    return count * _BYTES[dtype]


def audit(fn, *args, label: str, static_argnums=(), in_shardings=None,
          out_shardings=None) -> dict:
    """Compile; count collectives in the optimized HLO."""
    import jax

    jfn = jax.jit(fn, static_argnums=static_argnums,
                  in_shardings=in_shardings, out_shardings=out_shardings)
    hlo = jfn.lower(*args).compile().as_text()
    counts: dict = defaultdict(lambda: {"count": 0, "bytes": 0,
                                        "in_loop": 0})
    # attribute each instruction to its computation: collectives inside a
    # while/scan BODY execute once per round, so a static site inside a
    # loop stands for many dynamic executions
    lines = hlo.splitlines()
    loop_comps = set()
    for ls in lines:
        for m in re.finditer(r"(?:body|condition)=%?([\w.\-]+)", ls):
            loop_comps.add(m.group(1))
    comp = ""
    for line in lines:
        ls = line.strip()
        mc = re.match(r"%?([\w.\-]+)\s*\(.*\{\s*$", ls)
        if mc:
            comp = mc.group(1)
        # count op *instructions* (skip the done/start split duplicates)
        for c in COLLECTIVES:
            if re.search(rf"=\s*\S+\s+{c}(-start)?\(", ls):
                counts[c]["count"] += 1
                counts[c]["bytes"] += _op_bytes(ls)
                if comp in loop_comps:
                    counts[c]["in_loop"] += 1
    total = {"count": sum(v["count"] for v in counts.values()),
             "bytes": sum(v["bytes"] for v in counts.values()),
             "in_loop": sum(v["in_loop"] for v in counts.values())}
    row = {"label": label, "collectives": dict(counts), "total": total}
    print(f"{label}: {total['count']} collective sites "
          f"({total['in_loop']} inside loop bodies = per-round), "
          f"{total['bytes'] / 1e6:.3f} MB static payload")
    for c, v in sorted(counts.items()):
        print(f"    {c:20s} x{v['count']:3d} ({v['in_loop']} in-loop)  "
              f"{v['bytes'] / 1e6:.3f} MB")
    return row


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n", type=int, default=1000)
    ap.add_argument("--out", default=str(RESULTS / "collective_audit.json"))
    args = ap.parse_args(argv)

    import os
    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax
    import jax.numpy as jnp

    from aclswarm_tpu import sim
    from aclswarm_tpu.assignment import sinkhorn
    from aclswarm_tpu.core.types import (ControlGains, SafetyParams,
                                         make_formation)
    from aclswarm_tpu.parallel import mesh as meshlib

    n = args.n
    mesh = meshlib.make_mesh(n_agents=n)
    ndev = len(mesh.devices.ravel())
    assert ndev > 1, "need a multi-device mesh (set " \
        "XLA_FLAGS=--xla_force_host_platform_device_count=8)"
    rng = np.random.default_rng(0)
    rows = []

    # --- sharded control tick (the engine step at scale) ---
    pts = rng.normal(size=(n, 3)).astype(np.float32) * 20
    adj = (np.ones((n, n)) - np.eye(n)).astype(np.float32)
    gains = (rng.normal(size=(n, n, 3, 3)) * 0.01).astype(np.float32)
    f = make_formation(jnp.asarray(pts), jnp.asarray(adj),
                       jnp.asarray(gains))
    sp = SafetyParams(bounds_min=jnp.asarray([-100.0, -100.0, 0.0]),
                      bounds_max=jnp.asarray([100.0, 100.0, 20.0]))
    st = sim.init_state(
        rng.normal(size=(n, 3)).astype(np.float32) * 20 + [0, 0, 2])
    cfg = sim.SimConfig(assignment="none", colavoid_neighbors=16)
    st_put, f_put, st_sh, f_sh = meshlib.shard_problem(st, f, mesh)

    def tick(s, ff):
        return sim.step(s, ff, ControlGains(), sp, cfg)[0]

    rows.append(audit(tick, st_put, f_put,
                      label=f"control_tick_n{n}_dev{ndev}",
                      in_shardings=(st_sh, f_sh), out_shardings=st_sh))

    # --- sharded sinkhorn assignment ---
    q = jnp.asarray(rng.normal(size=(n, 3)).astype(np.float32) * 20)
    p = jnp.asarray(pts)
    row_sh = meshlib.row_sharding(mesh)
    rep = meshlib.replicated(mesh)
    q_put = jax.device_put(q, row_sh)

    rows.append(audit(
        lambda qq: sinkhorn.sinkhorn_assign(qq, p, n_iters=50).row_to_col,
        q_put, label=f"sinkhorn_assign_n{n}_dev{ndev}",
        in_shardings=(row_sh,), out_shardings=rep))

    # --- sharded sinkhorn with replicated rounding (the layout fix) ---
    rows.append(audit(
        lambda qq: sinkhorn.sinkhorn_assign(
            qq, p, n_iters=50, stage_shardings=(row_sh, rep)).row_to_col,
        q_put, label=f"sinkhorn_assign_n{n}_dev{ndev}_staged",
        in_shardings=(row_sh,), out_shardings=rep))

    out = {"n": n, "devices": ndev, "entries": rows}
    Path(args.out).parent.mkdir(parents=True, exist_ok=True)
    Path(args.out).write_text(json.dumps(out, indent=1))
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
