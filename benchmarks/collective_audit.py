"""Collective inventory of the sharded step: what GSPMD actually inserts.

Round-2 review: "shard the agent axis" had correctness evidence but no
communication story. This tool compiles the sharded kernels on a virtual
8-device mesh (identical partitioning decisions to a real v5e-8 — GSPMD
partitions by sharding annotations, not by backend), walks the optimized
HLO, and inventories every collective op with its payload bytes. Output:

    benchmarks/results/collective_audit.json

plus a human-readable table on stdout. The per-tick byte totals against
v5e ICI bandwidth (~400 GB/s/link bidirectional) give the expected
multi-chip scaling; see docs/SCALING.md for the analysis.

Run:  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
          python benchmarks/collective_audit.py [--n 1000]
"""
from __future__ import annotations

import argparse
import json
import re
import sys
from collections import defaultdict
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

RESULTS = Path(__file__).resolve().parent / "results"

COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter",
               "collective-permute", "all-to-all")

_SHAPE = re.compile(r"(f64|f32|bf16|f16|s32|u32|s8|u8|pred)\[([\d,]*)\]")
_BYTES = {"f64": 8, "f32": 4, "bf16": 2, "f16": 2, "s32": 4, "u32": 4,
          "s8": 1, "u8": 1, "pred": 1}


def _op_bytes(line: str) -> int:
    """Output payload bytes of one HLO op line (first shape on the line)."""
    m = _SHAPE.search(line)
    if not m:
        return 0
    dtype, dims = m.groups()
    count = int(np.prod([int(d) for d in dims.split(",") if d])) if dims \
        else 1
    return count * _BYTES[dtype]


def audit(fn, *args, label: str, static_argnums=(), in_shardings=None,
          out_shardings=None) -> dict:
    """Compile; count collectives in the optimized HLO."""
    import jax

    jfn = jax.jit(fn, static_argnums=static_argnums,
                  in_shardings=in_shardings, out_shardings=out_shardings)
    hlo = jfn.lower(*args).compile().as_text()
    counts: dict = defaultdict(lambda: {"count": 0, "bytes": 0,
                                        "in_loop": 0})
    # attribute each instruction to its computation: collectives inside a
    # while/scan BODY execute once per round, so a static site inside a
    # loop stands for many dynamic executions
    lines = hlo.splitlines()
    loop_comps = set()
    for ls in lines:
        for m in re.finditer(r"(?:body|condition)=%?([\w.\-]+)", ls):
            loop_comps.add(m.group(1))
    comp = ""
    for line in lines:
        ls = line.strip()
        mc = re.match(r"%?([\w.\-]+)\s*\(.*\{\s*$", ls)
        if mc:
            comp = mc.group(1)
        # count op *instructions* (skip the done/start split duplicates)
        for c in COLLECTIVES:
            if re.search(rf"=\s*\S+\s+{c}(-start)?\(", ls):
                counts[c]["count"] += 1
                counts[c]["bytes"] += _op_bytes(ls)
                if comp in loop_comps:
                    counts[c]["in_loop"] += 1
    total = {"count": sum(v["count"] for v in counts.values()),
             "bytes": sum(v["bytes"] for v in counts.values()),
             "in_loop": sum(v["in_loop"] for v in counts.values())}
    row = {"label": label, "collectives": dict(counts), "total": total}
    print(f"{label}: {total['count']} collective sites "
          f"({total['in_loop']} inside loop bodies = per-round), "
          f"{total['bytes'] / 1e6:.3f} MB static payload")
    for c, v in sorted(counts.items()):
        print(f"    {c:20s} x{v['count']:3d} ({v['in_loop']} in-loop)  "
              f"{v['bytes'] / 1e6:.3f} MB")
    return row


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n", type=int, default=1000)
    ap.add_argument("--out", default=str(RESULTS / "collective_audit.json"))
    args = ap.parse_args(argv)

    import os
    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax
    import jax.numpy as jnp

    from aclswarm_tpu import sim
    from aclswarm_tpu.assignment import sinkhorn
    from aclswarm_tpu.core.types import (ControlGains, SafetyParams,
                                         make_formation)
    from aclswarm_tpu.parallel import mesh as meshlib

    n = args.n
    mesh = meshlib.make_mesh(n_agents=n)
    ndev = len(mesh.devices.ravel())
    assert ndev > 1, "need a multi-device mesh (set " \
        "XLA_FLAGS=--xla_force_host_platform_device_count=8)"
    rng = np.random.default_rng(0)
    rows = []

    # --- sharded control tick (the engine step at scale) ---
    pts = rng.normal(size=(n, 3)).astype(np.float32) * 20
    adj = (np.ones((n, n)) - np.eye(n)).astype(np.float32)
    gains = (rng.normal(size=(n, n, 3, 3)) * 0.01).astype(np.float32)
    f = make_formation(jnp.asarray(pts), jnp.asarray(adj),
                       jnp.asarray(gains))
    sp = SafetyParams(bounds_min=jnp.asarray([-100.0, -100.0, 0.0]),
                      bounds_max=jnp.asarray([100.0, 100.0, 20.0]))
    st = sim.init_state(
        rng.normal(size=(n, 3)).astype(np.float32) * 20 + [0, 0, 2])
    cfg = sim.SimConfig(assignment="none", colavoid_neighbors=16)
    st_put, f_put, st_sh, f_sh = meshlib.shard_problem(st, f, mesh)

    def tick(s, ff):
        return sim.step(s, ff, ControlGains(), sp, cfg)[0]

    rows.append(audit(tick, st_put, f_put,
                      label=f"control_tick_n{n}_dev{ndev}",
                      in_shardings=(st_sh, f_sh), out_shardings=st_sh))

    # --- sharded sinkhorn assignment ---
    q = jnp.asarray(rng.normal(size=(n, 3)).astype(np.float32) * 20)
    p = jnp.asarray(pts)
    row_sh = meshlib.row_sharding(mesh)
    rep = meshlib.replicated(mesh)
    q_put = jax.device_put(q, row_sh)

    rows.append(audit(
        lambda qq: sinkhorn.sinkhorn_assign(qq, p, n_iters=50).row_to_col,
        q_put, label=f"sinkhorn_assign_n{n}_dev{ndev}",
        in_shardings=(row_sh,), out_shardings=rep))

    # --- sharded sinkhorn with replicated rounding (the layout fix) ---
    rows.append(audit(
        lambda qq: sinkhorn.sinkhorn_assign(
            qq, p, n_iters=50, stage_shardings=(row_sh, rep)).row_to_col,
        q_put, label=f"sinkhorn_assign_n{n}_dev{ndev}_staged",
        in_shardings=(row_sh,), out_shardings=rep))

    # --- sharded flooded-localization tick (the L3 merge at scale) -----
    # The one path measured below the 100 Hz bar on a single chip at
    # n=2000 (flooded_tick 41 Hz, scale_tpu_n2000.json): estimate tables
    # shard by owning agent, the min-age merge gathers neighbor rows over
    # ICI (mesh.sim_state_sharding docstring). B=64 matches the flown
    # configs. Same builders the crossover model compiles, so the audited
    # kernel and the modeled one cannot diverge.
    fn, fargs, in_sh, out_sh = _flood_builder(n, mesh)
    rows.append(audit(fn, *[jax.device_put(a, s)
                            for a, s in zip(fargs, in_sh)],
                      label=f"flooded_tick_n{n}_dev{ndev}_b64",
                      in_shardings=in_sh, out_shardings=out_sh))

    # --- sharded blocked-CBAA consensus round --------------------------
    # One synchronous bid round (n_iters=1, no early exit): the auction
    # is a sequence of identical rounds, so the per-round inventory and
    # partition ratio transfer to the whole auction (bit-identical path,
    # round count unchanged by sharding).
    fn, cargs, in_sh, out_sh = _cbaa_round_builder(n, mesh)
    rows.append(audit(fn, *[jax.device_put(a, s)
                            for a, s in zip(cargs, in_sh)],
                      label=f"cbaa_round_n{n}_dev{ndev}_b64",
                      in_shardings=in_sh, out_shardings=out_sh))

    # --- crossover cost model (round-3 weak #1) ------------------------
    # This box gives the virtual mesh ONE physical core
    # (os.cpu_count()=1), so a wall-clock sharded-vs-single crossover is
    # unobservable here BY CONSTRUCTION: 8 "devices" timeshare the same
    # silicon and collectives only add work. The crossover evidence is
    # therefore a cost model built from measurable quantities:
    #   * per-device compute from XLA's cost analysis of the ACTUAL
    #     compiled sharded vs unsharded programs (GSPMD partitions by
    #     annotations, identically on CPU and TPU);
    #   * collective payloads from the HLO inventory above;
    #   * the real chip's measured achieved FLOP/s for the same kernel
    #     (scale_tpu.json roofline fields) and public v5e ICI bandwidth.
    model = cost_model(mesh, n_list=(512, 1024, 2048, 4096))
    flood_model = path_cost_model(
        mesh, "flooded_tick_b64",
        _flood_builder, n_list=(1000, 2000, 4096),
        measured=_measured_rows("flooded_tick_n{n}_k16_b64_hz"),
        bar_hz=100.0)
    cbaa_model = path_cost_model(
        mesh, "cbaa_round_b64",
        _cbaa_round_builder, n_list=(1000, 2000),
        measured=_measured_rows("cbaa_faithful_earlyexit_n{n}_b64_hz"),
        bar_hz=None, per_round=True)
    out = {"n": n, "devices": ndev, "entries": rows,
           "crossover_model": model,
           "flood_crossover_model": flood_model,
           "cbaa_crossover_model": cbaa_model}
    Path(args.out).parent.mkdir(parents=True, exist_ok=True)
    Path(args.out).write_text(json.dumps(out, indent=1))
    print(f"wrote {args.out}")
    return 0


# v5e ICI: 4 links/chip, ~50 GB/s/direction each (public "How to Scale
# Your Model" numbers give ~4.5e10 B/s/link one-way); a ring all-gather
# of V bytes over D devices costs ~ V * (D-1)/D / W_link.
ICI_LINK_BPS = 4.5e10
# non-partitionable per-tick overhead assumed in the conservative model
# column: launch scheduling + per-collective ICI latency (~6 gathers x a
# few us, plus headroom). Deliberately pessimistic.
LATENCY_FLOOR_S = 100e-6


def _flops_bytes(jfn, *args) -> tuple:
    comp = jfn.lower(*args).compile()
    ca = comp.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return float(ca.get("flops", 0.0)), float(ca.get("bytes accessed", 0.0))


def cost_model(mesh, n_list=(1000, 2000, 4000, 8000)) -> dict:
    """Sharded-vs-single crossover model from compiled-program statistics.

    For each n: compile the engine control tick unsharded and sharded
    over the mesh, read XLA's flops estimate for both (the sharded
    number is PER DEVICE under SPMD), inventory the sharded program's
    collective bytes, and predict single-chip vs D-chip time using the
    real chip's measured achieved FLOP/s at n=1000 (compute term; both
    programs share it — same kernels, same dtype) plus a ring-collective
    term at v5e ICI bandwidth. Reports the modeled speedup and the n at
    which sharded beats single (the crossover the 1-core CI box cannot
    show on a clock).
    """
    import jax
    import jax.numpy as jnp

    from aclswarm_tpu import sim
    from aclswarm_tpu.core.types import (ControlGains, SafetyParams,
                                         make_formation)
    from aclswarm_tpu.parallel import mesh as meshlib

    ndev = len(mesh.devices.ravel())
    rng = np.random.default_rng(1)
    # Calibration: flop ESTIMATES differ across backends (TPU compilation
    # fuses away work the CPU HLO counts), so the model must use ONE flop
    # measure throughout — this process's CPU-HLO estimate — calibrated
    # against the real chip's measured tick rate from scale_tpu.json:
    #   achieved := cpu_hlo_flops(tick, n=1000) * measured_tpu_hz(n=1000)
    # Then t(n) = cpu_hlo_flops(n) / achieved reproduces the measured
    # n=1000 tick by construction and extrapolates by the flop ratio.
    tick_hz = 1000.0   # conservative fallback = the 100 Hz target x10
    art = RESULTS / "scale_tpu.json"
    if art.exists():
        for line in art.read_text().splitlines():
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue
            if row.get("metric", "").startswith("control_tick_n1000"):
                tick_hz = float(row["value"])
    achieved = None   # set from the n=1000 unsharded compile below
    rows = []
    cfg = sim.SimConfig(assignment="none", colavoid_neighbors=16)
    sp = SafetyParams(bounds_min=jnp.asarray([-100.0, -100.0, 0.0]),
                      bounds_max=jnp.asarray([100.0, 100.0, 20.0]))

    def build(n):
        pts = rng.normal(size=(n, 3)).astype(np.float32) * 20
        adj = (np.ones((n, n)) - np.eye(n)).astype(np.float32)
        gains = (rng.normal(size=(n, n, 3, 3)) * 0.01).astype(np.float32)
        f = make_formation(jnp.asarray(pts), jnp.asarray(adj),
                           jnp.asarray(gains))
        st = sim.init_state(
            rng.normal(size=(n, 3)).astype(np.float32) * 20 + [0, 0, 2])

        def tick(s, ff):
            return sim.step(s, ff, ControlGains(), sp, cfg)[0]

        return tick, st, f

    tick0, st0, f0 = build(1000)
    flops1000, _ = _flops_bytes(jax.jit(tick0), st0, f0)
    if flops1000 <= 0.0:      # backend offered no flop estimate
        flops1000 = 92e6      # the measured CPU-HLO value, pinned
    achieved = flops1000 * tick_hz
    print(f"cost_model calibration: cpu-hlo {flops1000 / 1e6:.1f} MFLOP "
          f"per n=1000 tick x measured {tick_hz:.0f} Hz -> "
          f"{achieved / 1e9:.0f} GFLOP/s equivalent")

    for n in n_list:
        tick, st, f = build(n)
        single_flops, _ = _flops_bytes(jax.jit(tick), st, f)

        st_put, f_put, st_sh, f_sh = meshlib.shard_problem(st, f, mesh)
        jsh = jax.jit(tick, in_shardings=(st_sh, f_sh),
                      out_shardings=st_sh)
        comp = jsh.lower(st_put, f_put).compile()   # one 8-way compile
        ca = comp.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        dev_flops = float(ca.get("flops", 0.0))
        hlo = comp.as_text()
        cbytes = sum(_op_bytes(ls) for ls in hlo.splitlines()
                     if any(re.search(rf"=\s*\S+\s+{c}(-start)?\(", ls)
                            for c in COLLECTIVES))
        t_single = single_flops / achieved
        t_comm = cbytes * (ndev - 1) / ndev / ICI_LINK_BPS
        t_shard = dev_flops / achieved + t_comm
        # conservative column: add a fixed per-tick floor for the costs
        # that do NOT partition — kernel-launch scheduling and collective
        # latency (~20 sites x ~5 us ICI latency). The truth lies between
        # the two columns; both beat single-chip at every n here.
        t_shard_floor = t_shard + LATENCY_FLOOR_S
        rows.append({
            "n": n,
            "single_flops": single_flops,
            "per_device_flops": dev_flops,
            "compute_partition_ratio": round(single_flops
                                             / max(dev_flops, 1.0), 2),
            "collective_bytes": cbytes,
            "modeled_t_single_us": round(t_single * 1e6, 1),
            "modeled_t_sharded_us": round(t_shard * 1e6, 1),
            "modeled_speedup": round(t_single / t_shard, 2),
            "modeled_speedup_with_latency_floor": round(
                t_single / t_shard_floor, 2),
        })
        ratio = rows[-1]["compute_partition_ratio"]
        print(f"cost_model n={n}: partition {ratio}x/dev, collectives "
              f"{cbytes / 1e6:.2f} MB, modeled speedup "
              f"{rows[-1]['modeled_speedup']}x "
              f"({rows[-1]['modeled_speedup_with_latency_floor']}x with "
              f"{LATENCY_FLOOR_S * 1e6:.0f} us floor)")
    cross = next((r["n"] for r in rows if r["modeled_speedup"] > 1.0),
                 None)
    return {"devices": ndev, "achieved_flops_s": achieved,
            "ici_link_Bps": ICI_LINK_BPS, "rows": rows,
            "modeled_crossover_n": cross,
            "note": "wall-clock crossover unobservable on this CI box: "
                    "the 8-device mesh shares 1 physical core "
                    "(os.cpu_count()=1); model built from compiled "
                    "per-device flops + HLO collective bytes + "
                    "real-chip achieved FLOP/s"}


def _measured_rows(metric_fmt: str) -> dict:
    """Pull measured single-chip rows from the committed scale artifacts
    (jsonl), keyed by n: {"hz": rate, "rounds": loop rounds if recorded}."""
    out = {}
    for fname, n in (("scale_tpu.json", 1000),
                     ("scale_tpu_n2000.json", 2000)):
        p = RESULTS / fname
        if not p.exists():
            continue
        want = metric_fmt.format(n=n)
        for line in p.read_text().splitlines():
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue
            if row.get("metric") == want:
                out[n] = {"hz": float(row["value"]),
                          "rounds": int(row["rounds"])
                          if "rounds" in row else None}
    return out


def _flood_builder(n, mesh):
    """The flooded-localization merge at scale knobs (B=64)."""
    import jax
    import jax.numpy as jnp

    from aclswarm_tpu.parallel import mesh as meshlib
    from aclswarm_tpu.sim import localization as loclib

    rng = np.random.default_rng(2)
    q = jnp.asarray(rng.normal(size=(n, 3)).astype(np.float32) * 20)
    adj = jnp.asarray((np.ones((n, n)) - np.eye(n)).astype(np.float32))
    v2f = jnp.arange(n, dtype=jnp.int32)
    loc = loclib.init_table(q)
    row = meshlib.row_sharding(mesh)
    rep = meshlib.replicated(mesh)
    loc_sh = loclib.EstimateTable(est=row, age=row)

    def flood(lc, qq, vv):
        return loclib.tick(lc, qq, adj, vv, jnp.asarray(True),
                           target_block=64)

    args = (loc, q, v2f)
    return flood, args, (loc_sh, row, rep), loc_sh


def _cbaa_round_builder(n, mesh):
    """One synchronous blocked-CBAA consensus round (B=64)."""
    import jax.numpy as jnp

    from aclswarm_tpu.assignment import cbaa
    from aclswarm_tpu.parallel import mesh as meshlib

    rng = np.random.default_rng(3)
    q = jnp.asarray(rng.normal(size=(n, 3)).astype(np.float32) * 20)
    pts = jnp.asarray(rng.normal(size=(n, 3)).astype(np.float32) * 20)
    adj = jnp.asarray((np.ones((n, n)) - np.eye(n)).astype(np.float32))
    v2f = jnp.arange(n, dtype=jnp.int32)
    row = meshlib.row_sharding(mesh)
    rep = meshlib.replicated(mesh)

    def rnd(qq, vv):
        return cbaa.cbaa_from_state(qq, pts, adj, vv, n_iters=1,
                                    task_block=64, early_exit=False).price

    return rnd, (q, v2f), (row, rep), rep


def path_cost_model(mesh, label, builder, n_list, measured,
                    bar_hz=None, per_round=False) -> dict:
    """Crossover model for one sharded path (round-4 review Missing #2:
    the flood merge and the CBAA consensus had no modeled multi-chip
    row — yet the flooded tick is the one metric below the 100 Hz bar
    at n=2000).

    Same methodology as `cost_model`, with one extension: these kernels
    are HBM-bound (the n=2000 flood runs at 14 % of single-chip HBM
    peak), so the calibration tracks BOTH the flop and bytes columns of
    the CPU-HLO estimate against the measured rate at the smallest
    measured n, and the compute term takes the binding resource
    (max of the two modeled times). Collective payloads ride the
    `cost_model` ring term at v5e ICI bandwidth.

    ``per_round=True`` labels paths whose builder compiles ONE iteration
    of a sequential consensus loop. The unit of this model is then a
    ROUND, in both columns: single-chip round time = measured auction
    time / measured round count (`scale.py` records `rounds` on the
    cbaa rows), and the comm + latency-floor terms apply once per round
    — NOT amortized over the auction. Sharding changes neither the
    round count nor any value (bit-identical path), so the whole-
    auction speedup equals the per-round speedup and
    modeled_auction_hz_sharded = measured auction Hz x that speedup.
    """
    import jax

    ndev = len(mesh.devices.ravel())
    if not measured:
        return {"error": "no measured single-chip rates in scale "
                         "artifacts; run benchmarks/scale.py first"}
    calib_n = min(measured)

    def unit_time(n):
        """Measured single-chip time of the modeled unit (tick or round)."""
        m = measured.get(n)
        if m is None:
            return None
        if per_round:
            if not m["rounds"]:
                return None
            return 1.0 / m["hz"] / m["rounds"]
        return 1.0 / m["hz"]

    fn, args, _, _ = builder(calib_n, mesh)
    f_calib, b_calib = _flops_bytes(jax.jit(fn), *args)
    t_calib = unit_time(calib_n)
    if t_calib is None or (f_calib <= 0.0 and b_calib <= 0.0):
        return {"error": "calibration impossible: no measured unit time "
                         "or backend offered no cost estimates"}
    # a backend may omit one column; an absent column simply never binds
    ach_f = f_calib / t_calib if f_calib > 0 else None
    ach_b = b_calib / t_calib if b_calib > 0 else None

    def model_t(f, b):
        ts = []
        if ach_f:
            ts.append(f / ach_f)
        if ach_b:
            ts.append(b / ach_b)
        return max(ts)

    single_cache = {calib_n: (f_calib, b_calib)}
    rows = []
    for n in n_list:
        fn, args, in_sh, out_sh = builder(n, mesh)
        if n not in single_cache:
            single_cache[n] = _flops_bytes(jax.jit(fn), *args)
        f_single, b_single = single_cache[n]
        t_single = model_t(f_single, b_single)
        jsh = jax.jit(fn, in_shardings=in_sh, out_shardings=out_sh)
        comp = jsh.lower(*args).compile()
        ca = comp.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        dev_f = float(ca.get("flops", 0.0))
        dev_b = float(ca.get("bytes accessed", 0.0))
        hlo = comp.as_text()
        cbytes = sum(_op_bytes(ls) for ls in hlo.splitlines()
                     if any(re.search(rf"=\s*\S+\s+{c}(-start)?\(", ls)
                            for c in COLLECTIVES))
        t_comm = cbytes * (ndev - 1) / ndev / ICI_LINK_BPS
        t_shard = model_t(dev_f, dev_b) + t_comm + LATENCY_FLOOR_S
        unit = "round" if per_round else "tick"
        m = measured.get(n)
        row = {
            "n": n,
            "unit": unit,
            "measured_hz": m["hz"] if m else None,
            "measured_rounds": m["rounds"] if m else None,
            "measured_unit_ms": (round(unit_time(n) * 1e3, 3)
                                 if unit_time(n) else None),
            "modeled_unit_single_ms": round(t_single * 1e3, 3),
            "collective_bytes": cbytes,
            "modeled_unit_sharded_ms": round(t_shard * 1e3, 3),
            "modeled_speedup": round(t_single / t_shard, 2),
        }
        if per_round:
            if m:
                row["modeled_auction_hz_sharded"] = round(
                    m["hz"] * row["modeled_speedup"], 2)
        else:
            row["modeled_sharded_hz"] = round(1.0 / t_shard, 1)
            row["modeled_single_hz"] = round(1.0 / t_single, 1)
            if bar_hz is not None:
                row["clears_bar"] = bool(1.0 / t_shard >= bar_hz)
        rows.append(row)
        extra = f" (measured {m['hz']:.1f} Hz)" if m else ""
        print(f"{label} n={n}: modeled {unit} "
              f"{row['modeled_unit_single_ms']} ms single -> "
              f"{row['modeled_unit_sharded_ms']} ms sharded "
              f"({row['modeled_speedup']}x, {cbytes / 1e6:.1f} MB "
              f"collectives){extra}")
    out = {"devices": ndev, "label": label, "bar_hz": bar_hz,
           "per_round": per_round, "calibration_n": calib_n,
           "measured": measured, "rows": rows,
           "note": "compute term = max(flop, bytes) column of the "
                   "CPU-HLO estimate calibrated to the measured "
                   "single-chip unit time (per ROUND for per_round "
                   "paths — comm + latency floor charged once per "
                   "round, not amortized over the auction); "
                   "collectives ride the ring term at v5e ICI "
                   "bandwidth"}
    if bar_hz is not None:
        out["bar_reachable_n"] = [r["n"] for r in rows
                                  if r.get("clears_bar")]
    return out


if __name__ == "__main__":
    sys.exit(main())
