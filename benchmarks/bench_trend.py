"""bench_trend — make the BENCH_r*.json trajectory visible and guarded.

Every driver round commits a `BENCH_r<NN>.json` capture at the repo
root (the structured one-line `bench.py` row plus its exit status),
but nothing ever compared them: the trajectory was invisible, and a
silent throughput regression would ride along unnoticed. This tool:

- parses every round's ``parsed`` row (the bench metric), skipping
  rounds that recorded an ``error`` or a non-positive value (a wedged
  tunnel is evidence of the environment, not of the code);
- prints the per-metric trajectory (round, value, delta vs previous
  comparable round);
- exits NONZERO when the newest comparable round regresses more than
  ``--threshold`` (default 10%) against the previous comparable round
  of the same metric — direction-aware (``Hz`` is higher-better,
  ``s``/``us``/``ms`` lower-better).

Run:

    python benchmarks/bench_trend.py [--dir .] [--threshold 0.10] [--soft]

``--soft`` reports but always exits 0 (informational mode for gates
that must not fail on a historical regression already being worked).
"""
from __future__ import annotations

import argparse
import json
import re
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent

# direction per unit: +1 = higher is better (rates), -1 = lower is
# better (latencies); unknown units default to higher-better
_DIRECTION = {"Hz": 1, "hz": 1, "s": -1, "ms": -1, "us": -1,
              "ratio": -1, "iters": -1, "frac": -1}


def load_rounds(directory: Path) -> list[tuple[int, dict]]:
    """[(round, parsed-row)] for every BENCH_r*.json, round-ordered.
    A capture may carry ONE row (``parsed``, the bench.py flagship) or
    a LIST (``parsed_rows``) — multi-metric rounds trend per series
    key, exactly like the single row always did."""
    out = []
    for path in sorted(directory.glob("BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path.name)
        if not m:
            continue
        try:
            cap = json.loads(path.read_text())
        except json.JSONDecodeError as e:
            print(f"WARN: {path.name} unparseable ({e}) — skipped")
            continue
        rnd = int(m.group(1))
        parsed = cap.get("parsed")
        if isinstance(parsed, dict):
            out.append((rnd, parsed))
        extra = cap.get("parsed_rows")
        if isinstance(extra, list):
            out.extend((rnd, r) for r in extra if isinstance(r, dict))
    # NUMERIC round order, not the glob's lexical filename order —
    # BENCH_r100 sorts between r10 and r11 lexically, which would
    # compare non-adjacent rounds and mis-pick the newest
    out.sort(key=lambda t: t[0])
    return out


# the committed overload surface (benchmarks/results/serve_overload.json)
# contributes trend rows: goodput + p99 at the 1x and 10x offered-load
# levels — the serve-SLO numbers that must not silently rot between
# rounds. They join the series map as a pseudo-round AFTER the newest
# BENCH capture (the artifact is the repo's CURRENT state), so any
# historical capture carrying the same series gates the transition.
OVERLOAD_LEVELS = ("1x", "10x")


def overload_rows(results_dir: Path | None = None) -> list[dict]:
    """Trend-shaped rows from the committed serve_overload artifact:
    ``serve_overload_goodput`` (Hz, higher-better) and
    ``serve_overload_p99`` (s, lower-better) at each of the 1x and 10x
    levels, keyed by the ``level`` discriminator."""
    results_dir = results_dir or (ROOT / "benchmarks" / "results")
    path = results_dir / "serve_overload.json"
    if not path.exists():
        return []
    rows = []
    for line in path.read_text().strip().splitlines():
        try:
            r = json.loads(line)
        except json.JSONDecodeError:
            continue
        if not isinstance(r, dict) or r.get("quick") \
                or r.get("level") not in OVERLOAD_LEVELS:
            continue
        common = {"level": r["level"], "n": r.get("n"),
                  "backend": r.get("backend")}
        rows.append(dict(common, name="serve_overload_goodput",
                         value=r.get("value"), unit="Hz"))
        rows.append(dict(common, name="serve_overload_p99",
                         value=r.get("p99_s"), unit="s"))
    return rows


def slo_detection_rows(results_dir: Path | None = None) -> list[dict]:
    """Trend-shaped rows from the committed slo_detection artifact
    (benchmarks/slo_soak.py): ``slo_detection_p95`` — the kill→alert
    detection latency p95 (s, lower-better) swarmwatch proves. Joins
    the series map exactly like the overload rows: as the pseudo-round
    after the newest capture."""
    results_dir = results_dir or (ROOT / "benchmarks" / "results")
    path = results_dir / "slo_detection.json"
    if not path.exists():
        return []
    try:
        r = json.loads(path.read_text())
    except json.JSONDecodeError:
        return []
    if not isinstance(r, dict) or r.get("quick"):
        return []
    det = r.get("detection_s")
    p95 = det.get("p95") if isinstance(det, dict) else None
    if not isinstance(p95, (int, float)) or p95 <= 0:
        return []
    return [{"name": "slo_detection_p95", "value": p95, "unit": "s",
             "n": r.get("n"), "backend": r.get("backend")}]


def pipeline_rows(results_dir: Path | None = None) -> list[dict]:
    """Trend-shaped rows from the committed pipeline_n1000 artifact
    (benchmarks/pipeline_rate.py): ``pipeline_n1000_hz`` — the ROADMAP
    item 1 headline rate (Hz, higher-better) per warm/cold mode;
    ``admm_warm_iters`` — warm re-convergence iterations (lower-better:
    a creeping iteration count is the warm start rotting); and
    ``assign_churn_rate`` — reassignment fraction per hysteresis level
    (lower-better). Joins the series map as the pseudo-round after the
    newest capture, like the overload rows."""
    results_dir = results_dir or (ROOT / "benchmarks" / "results")
    path = results_dir / "pipeline_n1000.json"
    if not path.exists():
        return []
    rows = []
    for line in path.read_text().strip().splitlines():
        try:
            r = json.loads(line)
        except json.JSONDecodeError:
            continue
        if not isinstance(r, dict) or r.get("quick"):
            continue
        name = r.get("name")
        if name == "pipeline_rate" and r.get("n") == 1000:
            warm = "warm" if r.get("warm_gains") else "cold"
            rows.append({"name": "pipeline_n1000_hz",
                         "value": r.get("value"), "unit": "Hz",
                         "n": r.get("n"), "backend": r.get("backend"),
                         "level": f"{r.get('mode')}/{warm}"})
        elif name == "admm_warm_start":
            rows.append({"name": "admm_warm_iters",
                         "value": r.get("warm_iters"), "unit": "iters",
                         "n": r.get("n"), "backend": r.get("backend")})
        elif name == "assign_churn" and r.get("warm_tables"):
            rows.append({"name": "assign_churn_rate",
                         "value": r.get("churn_rate"), "unit": "frac",
                         "n": r.get("n"),
                         "level": f"eps={r.get('assign_eps')}"})
    return rows


def router_rows(results_dir: Path | None = None) -> list[dict]:
    """Trend-shaped rows from the committed router_fleet artifact
    (benchmarks/router_fleet.py): ``router_goodput_hz`` (Hz,
    higher-better) and ``router_p99_ms`` (ms, lower-better) per
    offered-load level — the cross-process serving surface that must
    not silently rot. Drill rows are excluded: kills are a chaos
    count, not a trendable rate. Joins the series map as the
    pseudo-round after the newest capture, like the overload rows."""
    results_dir = results_dir or (ROOT / "benchmarks" / "results")
    path = results_dir / "router_fleet.json"
    if not path.exists():
        return []
    rows = []
    for line in path.read_text().strip().splitlines():
        try:
            r = json.loads(line)
        except json.JSONDecodeError:
            continue
        if not isinstance(r, dict) or r.get("quick") \
                or r.get("level") == "drill" \
                or r.get("name") != "router_fleet":
            continue
        common = {"level": r.get("level"), "n": r.get("n"),
                  "backend": r.get("backend")}
        rows.append(dict(common, name="router_goodput_hz",
                         value=r.get("value"), unit="Hz"))
        p99 = r.get("p99_s")
        if isinstance(p99, (int, float)) and p99 > 0:
            rows.append(dict(common, name="router_p99_ms",
                             value=p99 * 1e3, unit="ms"))
    return rows


def _comparable(row: dict) -> bool:
    v = row.get("value")
    return (isinstance(v, (int, float)) and not isinstance(v, bool)
            and v > 0 and "error" not in row)


# discriminator fields folded into the series key when present: rows
# like serve_stage carry one (name, unit) per STAGE per shape per
# backend (and serve_overload rows one per offered-load LEVEL), and
# matching by name alone would compare pack against unpack — or 1x
# against 10x — across rounds: a meaningless delta that can both mask
# a real regression and invent a fake one
_SERIES_KEYS = ("stage", "n", "backend", "level")


def series_key(row: dict) -> str | None:
    """The comparability key a row trends under: its name plus any
    discriminator fields it carries (stage/n/backend). Rows without
    discriminators keep their bare name, so existing BENCH_r* series
    are unbroken."""
    name = row.get("name", row.get("metric"))
    if not (isinstance(name, str) and name):
        return None
    disc = [f"{k}={row[k]}" for k in _SERIES_KEYS if k in row]
    return name + (" [" + ", ".join(disc) + "]" if disc else "")


def series(rounds: list[tuple[int, dict]]) -> dict[str, list]:
    """comparability key -> [(round, row)] (legacy 'metric' key
    accepted; see `series_key`)."""
    by: dict[str, list] = {}
    for rnd, row in rounds:
        key = series_key(row)
        if key is not None:
            by.setdefault(key, []).append((rnd, row))
    return by


def trend(directory: Path, threshold: float) -> tuple[list[str], int]:
    """(report lines, regression count) over every metric series —
    the BENCH_r* captures plus the committed overload surface (as the
    round after the newest capture: the artifact is current state, so
    a capture that carried the same series gates the transition)."""
    rounds = load_rounds(directory)
    # a repo-shaped --dir (tests, forks) provides its own artifact;
    # a bare captures directory falls back to THIS repo's committed
    # results — the overload gate must not silently vanish just
    # because --dir pointed somewhere without a benchmarks/ tree
    res_dir = directory / "benchmarks" / "results"
    over = overload_rows(res_dir)
    slo = slo_detection_rows(res_dir)
    pipe = pipeline_rows(res_dir)
    rout = router_rows(res_dir)
    if directory.resolve() != ROOT.resolve():
        # PER-FAMILY fallback to this repo's committed results: a
        # capture dir carrying one artifact but not the other must not
        # silently drop the missing family's gate
        over = over or overload_rows()
        slo = slo or slo_detection_rows()
        pipe = pipe or pipeline_rows()
        rout = rout or router_rows()
    cur = over + slo + pipe + rout
    if cur:
        nxt = (rounds[-1][0] if rounds else 0) + 1
        rounds.extend((nxt, r) for r in cur)
    lines, regressions = [], 0
    if not rounds:
        return ([f"no BENCH_r*.json captures under {directory}"], 0)
    for name, pts in sorted(series(rounds).items()):
        unit = next((r.get("unit") for _, r in pts
                     if isinstance(r.get("unit"), str)), "")
        sign = _DIRECTION.get(unit, 1)
        lines.append(f"{name} [{unit or '?'}]:")
        newest = next((rnd for rnd, row in reversed(pts)
                       if _comparable(row)), None)
        prev = None
        for rnd, row in pts:
            v = row.get("value")
            if not _comparable(row):
                why = row.get("error", f"value={v!r}")
                lines.append(f"  r{rnd:02d}  --        "
                             f"(incomparable: {str(why)[:60]})")
                continue
            mark = ""
            if prev is not None:
                change = (v - prev[1]) / prev[1]
                arrow = "+" if change >= 0 else ""
                mark = f"{arrow}{change * 100:.1f}% vs r{prev[0]:02d}"
                if sign * change < -threshold:
                    # only the transition INTO the newest comparable
                    # round gates: a historical dip the trajectory has
                    # since recovered from is visible but not fatal —
                    # otherwise one bad round would redden the gate
                    # forever
                    if rnd == newest:
                        mark += f"  << REGRESSION (> {threshold:.0%})"
                        regressions += 1
                    else:
                        mark += "  (dip, since superseded)"
            lines.append(f"  r{rnd:02d}  {v:<10g}{mark}")
            prev = (rnd, v)
    return lines, regressions


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--dir", default=str(ROOT),
                    help="directory holding the BENCH_r*.json captures")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="relative regression bar (default 0.10)")
    ap.add_argument("--soft", action="store_true",
                    help="report only — exit 0 even on regression")
    args = ap.parse_args(argv)
    lines, regressions = trend(Path(args.dir), args.threshold)
    for ln in lines:
        print(ln)
    if regressions:
        print(f"\nBENCH TREND: {regressions} metric(s) regressed more "
              f"than {args.threshold:.0%} in their newest comparable "
              "round")
        return 0 if args.soft else 1
    print("\nBENCH TREND: no regression past the "
          f"{args.threshold:.0%} bar")
    return 0


if __name__ == "__main__":
    sys.exit(main())
