"""serve_throughput — request Hz vs batch-bucket occupancy vs offered
load (ROADMAP open item 2(c): the owed continuous-batching artifact).

The tunnel-TPU regime pays a fixed ~108 ms dispatch floor per device
launch; the whole case for swarmserve's continuous batching is that the
floor is paid ONCE per chunk round for every request packed into the
bucket. This benchmark makes that win measurable: sweep offered load
(requests/s) over a fixed-size service, and for each level report the
achieved terminal-request rate next to the mean/p95 bucket occupancy
and queue depth the swarmscope registry sampled at every chunk
boundary. Low load = mostly-empty buckets (each request pays the floor
alone); saturating load = full buckets (the floor amortizes B-ways) +
admission rejections doing their bounded-queue job.

Requests are single-chunk n=5 rollouts (the smallest real unit of
device work the service schedules), submitted by paced client threads
round-robin across three tenants. One service per level, fresh
registry; a warmup service run first keeps compile time out of every
measured level.

Run:

    JAX_PLATFORMS=cpu python benchmarks/serve_throughput.py [--quick] \
        [--out benchmarks/results/serve_throughput.json]

Exit 1 if any accepted request fails to terminate (the serve contract
is part of what this measures). Rows are schema-guarded by
`benchmarks/check_results.py::check_serve_throughput` (exact key set).
"""
from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

RESULTS = Path(__file__).resolve().parent / "results"

N = 5                     # rollout shape (one bucket; packing is the point)
TICKS = 60                # 3-chunk requests: jobs stay resident across
#                           rounds, so concurrent arrivals actually pack
# The >= 3 committed offered-load levels (requests/s), chosen to
# bracket the measured single-stream capacity of this host (~100
# requests/s at ~8-10 ms per solo request): light (buckets stay at one
# slot — latency-optimal), at-capacity (the rate a no-batching service
# would cap at), and saturating (buckets fill to ~1.0 occupancy, the
# achieved rate EXCEEDS single-stream capacity because the per-round
# cost amortizes across max_batch slots, and admission sheds the rest).
OFFERED_HZ = (16.0, 100.0, 400.0)
OFFERED_HZ_QUICK = (8.0, 64.0)
DURATION_S = 6.0
DURATION_S_QUICK = 2.5
TENANTS = ("alpha", "beta", "gamma")


def _service():
    from aclswarm_tpu.serve import ServiceConfig, SwarmService

    # modest caps so the saturating level provably exercises admission
    # backpressure; no journal — this is a throughput measurement, not
    # a durability drill (serve_soak.py owns that)
    return SwarmService(ServiceConfig(
        max_batch=4, quantum_chunks=4, max_queue_per_tenant=8,
        max_queue_total=24, idle_poll_s=0.01))


def _warmup() -> str:
    """Compile the rollout bucket once, outside every measured level."""
    import jax

    svc = _service()
    t = svc.submit("rollout", {"n": N, "ticks": TICKS,
                               "chunk_ticks": TICKS, "seed": 0})
    res = t.result(timeout=600)
    assert res.ok, f"warmup failed: {res}"
    svc.close()
    return jax.default_backend()


def run_level(offered_hz: float, duration_s: float) -> dict:
    """One offered-load level: paced submissions for ``duration_s``,
    then drain every ticket to a terminal result and read the stats."""
    from aclswarm_tpu.serve import RejectedError

    svc = _service()
    tickets = []
    t0 = time.perf_counter()
    i = 0
    # paced open-loop submission: request i is due at t0 + i/offered_hz
    # regardless of how the service is keeping up (closed-loop pacing
    # would hide saturation — the point is to offer MORE than it drains)
    while True:
        due = t0 + i / offered_hz
        now = time.perf_counter()
        if due > t0 + duration_s:
            break
        if due > now:
            time.sleep(due - now)
        try:
            tickets.append(svc.submit(
                "rollout",
                {"n": N, "ticks": TICKS, "chunk_ticks": TICKS,
                 "seed": i},
                tenant=TENANTS[i % len(TENANTS)],
                request_id=f"lvl{offered_hz:g}-{i}"))
        except RejectedError:
            pass     # backpressure; counted by the service registry
        i += 1
    # drain every accepted ticket to a terminal result; a ticket still
    # unresolved after its bounded wait is a broken serve promise and
    # counts as failed (surfaced as the FAIL exit in main, not a hang)
    results, non_terminal = [], 0
    for t in tickets:
        try:
            results.append(t.result(timeout=600))
        except TimeoutError:
            non_terminal += 1
    wall = time.perf_counter() - t0
    svc.close()
    st = svc.serve_stats()
    completed = sum(1 for r in results if r.ok)
    return {
        "completed": completed, "wall_s": wall, "stats": st,
        "failed": sum(1 for r in results if not r.ok) + non_terminal,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true",
                    help="2 short levels (CI smoke; artifact not "
                    "committed)")
    ap.add_argument("--out", default=str(RESULTS / "serve_throughput.json"),
                    help="artifact path ('' to skip writing)")
    args = ap.parse_args(argv)

    levels = OFFERED_HZ_QUICK if args.quick else OFFERED_HZ
    dur = DURATION_S_QUICK if args.quick else DURATION_S
    backend = _warmup()

    rows = []
    broken = 0
    for hz in levels:
        r = run_level(hz, dur)
        st = r["stats"]
        broken += r["failed"]
        row = {
            "name": "serve_throughput",
            "n": N,
            "backend": backend,
            "offered_hz": round(hz, 3),
            "value": round(r["completed"] / r["wall_s"], 3),
            "unit": "Hz",
            "occupancy_mean": round(st.occupancy_mean, 4),
            "occupancy_p95": round(st.occupancy_p95, 4),
            "queue_depth_mean": round(st.queue_depth_mean, 3),
            "queue_depth_p95": round(st.queue_depth_p95, 3),
            "accepted": st.counts["accepted"],
            "completed": r["completed"],
            "rejected": st.counts["rejected"],
            "preempted": st.counts["preempted"],
            "deadline_miss": st.counts["deadline_miss"],
            "wall_s": round(r["wall_s"], 2),
            "quick": bool(args.quick),
        }
        rows.append(row)
        print(json.dumps(row), flush=True)
    if broken:
        print(f"FAIL: {broken} accepted request(s) did not complete")
        return 1
    if args.out:
        p = Path(args.out)
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
        print(f"wrote {p}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
