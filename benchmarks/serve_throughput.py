"""serve_throughput — request Hz vs batch-bucket occupancy vs offered
load (ROADMAP open item 2(c): the owed continuous-batching artifact).

The tunnel-TPU regime pays a fixed ~108 ms dispatch floor per device
launch; the whole case for swarmserve's continuous batching is that the
floor is paid ONCE per chunk round for every request packed into the
bucket. This benchmark makes that win measurable: sweep offered load
(requests/s) over a fixed-size service, and for each level report the
achieved terminal-request rate next to the mean/p95 bucket occupancy
and queue depth the swarmscope registry sampled at every chunk
boundary. Low load = mostly-empty buckets (each request pays the floor
alone); saturating load = full buckets (the floor amortizes B-ways) +
admission rejections doing their bounded-queue job.

Requests are single-chunk n=5 rollouts (the smallest real unit of
device work the service schedules), submitted by paced client threads
round-robin across three tenants. One service per level, fresh
registry; a warmup service run first keeps compile time out of every
measured level.

Run:

    JAX_PLATFORMS=cpu python benchmarks/serve_throughput.py [--quick] \
        [--out benchmarks/results/serve_throughput.json]

Exit 1 if any accepted request fails to terminate (the serve contract
is part of what this measures). Rows are schema-guarded by
`benchmarks/check_results.py::check_serve_throughput` (exact key set).
"""
from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

RESULTS = Path(__file__).resolve().parent / "results"

N = 5                     # rollout shape (one bucket; packing is the point)
TICKS = 60                # 3-chunk requests: jobs stay resident across
#                           rounds, so concurrent arrivals actually pack
# The >= 3 committed offered-load levels (requests/s), chosen to
# bracket the measured single-stream capacity of this host (~100
# requests/s at ~8-10 ms per solo request): light (buckets stay at one
# slot — latency-optimal), at-capacity (the rate a no-batching service
# would cap at), the PR-7 saturation point (107 req/s then — the
# staged round now absorbs this whole level, >= 3x), and a 1000 Hz
# level that saturates even the staged path (buckets at ~1.0
# occupancy, admission shedding the rest — the backpressure evidence).
OFFERED_HZ = (16.0, 100.0, 400.0, 1000.0)
OFFERED_HZ_QUICK = (8.0, 64.0)
DURATION_S = 6.0
DURATION_S_QUICK = 2.5
TENANTS = ("alpha", "beta", "gamma")


def _service(start: bool = True):
    from aclswarm_tpu.serve import ServiceConfig, SwarmService

    # modest caps so the saturating level provably exercises admission
    # backpressure; no journal — this is a throughput measurement, not
    # a durability drill (serve_soak.py owns that)
    return SwarmService(ServiceConfig(
        max_batch=4, quantum_chunks=4, max_queue_per_tenant=8,
        max_queue_total=24, idle_poll_s=0.01), start=start)


def _warmup() -> str:
    """Compile every shape the measured levels can reach, outside the
    measurement. Queueing exactly ``b`` requests on a NOT-yet-started
    service guarantees the first round packs min(b, max_batch) — so
    every power-of-two batch shape (rollout + the serve.staging
    write/gather/scatter/unpack ops) lands in the process-wide jit
    cache deterministically, and the 24-burst additionally exercises
    the staging store at full occupancy with admission engaged. A
    level's fresh service must find every shape pre-compiled, or its
    6 s window measures the compiler instead of the scheduler."""
    import jax

    for b in (1, 2, 4, 24):
        svc = _service(start=False)
        tickets = []
        for i in range(b):
            tickets.append(svc.submit(
                "rollout", {"n": N, "ticks": TICKS,
                            "chunk_ticks": TICKS, "seed": 1000 * b + i},
                tenant=TENANTS[i % len(TENANTS)]))
        svc.start()
        for t in tickets:
            res = t.result(timeout=600)
            assert res.ok, f"warmup (b={b}) failed: {res}"
        svc.close()
    return jax.default_backend()


def run_level(offered_hz: float, duration_s: float) -> dict:
    """One offered-load level: paced submissions for ``duration_s``,
    then drain every ticket to a terminal result and read the stats.

    One paced client thread PER TENANT (offered_hz split evenly):
    since PR 11 moved request prep to submit time, a single client
    thread saturates at its own submit rate (~1 ms per accepted
    request) long before the staged service does — the level must
    measure the SERVICE's capacity, not one client's."""
    from aclswarm_tpu.serve import RejectedError

    svc = _service()
    tickets: list = []
    tlock = threading.Lock()
    per_hz = offered_hz / len(TENANTS)

    def client(k: int, tenant: str, t0: float) -> None:
        i = 0
        # paced open-loop submission: request i is due at
        # t0 + i/per_hz regardless of how the service is keeping up
        # (closed-loop pacing would hide saturation — the point is to
        # offer MORE than it drains)
        while True:
            due = t0 + i / per_hz
            now = time.perf_counter()
            if due > t0 + duration_s:
                return
            if due > now:
                time.sleep(due - now)
            try:
                t = svc.submit(
                    "rollout",
                    {"n": N, "ticks": TICKS, "chunk_ticks": TICKS,
                     "seed": 1_000_000 * k + i},
                    tenant=tenant,
                    request_id=f"lvl{offered_hz:g}-{tenant}-{i}")
                with tlock:
                    tickets.append(t)
            except RejectedError:
                pass     # backpressure; counted by the service registry
            i += 1

    t0 = time.perf_counter()
    clients = [threading.Thread(target=client, args=(k, tenant, t0))
               for k, tenant in enumerate(TENANTS)]
    for c in clients:
        c.start()
    for c in clients:
        c.join()
    # drain every accepted ticket to a terminal result; a ticket still
    # unresolved after its bounded wait is a broken serve promise and
    # counts as failed (surfaced as the FAIL exit in main, not a hang)
    results, non_terminal = [], 0
    for t in tickets:
        try:
            results.append(t.result(timeout=600))
        except TimeoutError:
            non_terminal += 1
    wall = time.perf_counter() - t0
    svc.close()
    st = svc.serve_stats()
    completed = sum(1 for r in results if r.ok)
    return {
        "completed": completed, "wall_s": wall, "stats": st,
        "failed": sum(1 for r in results if not r.ok) + non_terminal,
        "stage_fracs": _stage_fracs(svc),
    }


STAGES = ("pack", "stack", "dispatch", "device_sync", "unpack",
          "resolve")
# host-side stages of the round (the 90%+ the PR-9 breakdown exposed;
# the staged path owes their collapse — docs/SERVICE.md §scheduling)
HOST_STAGES = ("pack", "stack", "unpack")


def _stage_fracs(svc) -> dict:
    """Per-round stage fractions from this level's own span histograms:
    the attribution that makes the req/s jump explainable in ONE
    artifact (stage sum / serve.round sum, the latency-breakdown
    convention)."""
    def _sum(name):
        return float(svc.telemetry.histogram(name).to_row()
                     .get("sum", 0.0))

    rs = _sum("span_serve.round_s")
    return {s: (round(_sum(f"span_serve.round.{s}_s") / rs, 4)
                if rs else 0.0)
            for s in STAGES}


# the PR-7 committed rows on this host (benchmarks/results/
# serve_throughput.json before PR 11; see git history) — the ``speedup``
# column is the single-worker req/s jump the staged round owes vs that
# capture, offered-load level by level
R7_BASELINE_HZ = {16.0: 16.134, 100.0: 55.273, 400.0: 107.267}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true",
                    help="2 short levels (CI smoke; artifact not "
                    "committed)")
    ap.add_argument("--out", default=str(RESULTS / "serve_throughput.json"),
                    help="artifact path ('' to skip writing)")
    args = ap.parse_args(argv)

    levels = OFFERED_HZ_QUICK if args.quick else OFFERED_HZ
    dur = DURATION_S_QUICK if args.quick else DURATION_S
    backend = _warmup()

    rows = []
    broken = 0
    for hz in levels:
        r = run_level(hz, dur)
        st = r["stats"]
        broken += r["failed"]
        hz_achieved = round(r["completed"] / r["wall_s"], 3)
        base = R7_BASELINE_HZ.get(hz)
        fr = r["stage_fracs"]
        row = {
            "name": "serve_throughput",
            "n": N,
            "backend": backend,
            "offered_hz": round(hz, 3),
            "value": hz_achieved,
            "unit": "Hz",
            "speedup": (round(hz_achieved / base, 3)
                        if base else 0.0),
            "stage_fracs": fr,
            "host_frac": round(sum(fr[s] for s in HOST_STAGES), 4),
            "occupancy_mean": round(st.occupancy_mean, 4),
            "occupancy_p95": round(st.occupancy_p95, 4),
            "queue_depth_mean": round(st.queue_depth_mean, 3),
            "queue_depth_p95": round(st.queue_depth_p95, 3),
            "accepted": st.counts["accepted"],
            "completed": r["completed"],
            "rejected": st.counts["rejected"],
            "preempted": st.counts["preempted"],
            "deadline_miss": st.counts["deadline_miss"],
            "wall_s": round(r["wall_s"], 2),
            "quick": bool(args.quick),
        }
        rows.append(row)
        print(json.dumps(row), flush=True)
    if broken:
        print(f"FAIL: {broken} accepted request(s) did not complete")
        return 1
    if args.out:
        p = Path(args.out)
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
        print(f"wrote {p}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
