"""Multi-worker chaos soak for swarmserve — the worker-failover
flagship benchmark (docs/SERVICE.md §multi-worker; ROADMAP open item
2(b)).

Three tenants submit a mixed stream (two rollout shape buckets, n=5
and n=8, several carrying `FaultSchedule` scripts, plus single-shot
assignment/gain-design work) into an N=3-worker journaled service
while scripted `CrashPlan`s repeatedly SIGKILL individual workers
MID-BATCH (thread-abrupt death: in-flight work orphaned with no
cleanup — the same observable a killed worker process leaves) and one
deliberately POISONED request kills every worker that touches it. The
parent audits the fleet's promises:

- **zero silent losses**: every accepted request reaches a terminal
  result AND a journal done-frame — across every worker kill;
- **bit-identical migrated resume**: every completed rollout's digest
  matches an uncontended single-worker reference run, including the
  requests that migrated workers mid-flight (checkpoint-codec
  migration, `Result.failovers > 0`);
- **poison bound**: the poisoned request terminates with a structured
  ``poisoned`` error after ``max_worker_exclusions`` distinct kills —
  it cannot ping-pong the fleet;
- **fairness under failover**: no tenant is starved while the fleet
  degrades — every tenant's first completion lands within the first
  ``2 x tenants`` completions (the round-robin guarantee, now asserted
  THROUGH worker churn);
- **latency SLO evidence**: p50/p95/p99 accepted→terminal wall
  latency, committed to
  `benchmarks/results/serve_multiworker_soak.json` (exact-key-set
  schema: `benchmarks/check_results.py`).

Run:

    JAX_PLATFORMS=cpu python benchmarks/serve_multiworker_soak.py \
        [--quick] [--out benchmarks/results/serve_multiworker_soak.json]

Exit 1 on any broken promise — the artifact is only committed from a
green run.
"""
from __future__ import annotations

import argparse
import json
import sys
import tempfile
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

RESULTS = Path(__file__).resolve().parent / "results"
WORKERS = 3
TENANTS = ("alpha", "beta", "gamma")


def request_mix(quick: bool) -> list[dict]:
    """Deterministic mixed stream: two rollout shape buckets + faults +
    single-shot kinds, spread across three tenants."""
    ticks = 60 if quick else 120
    mix = [
        {"kind": "rollout", "tenant": "alpha", "request_id": "a-roll0",
         "params": {"n": 5, "ticks": ticks, "chunk_ticks": 20,
                    "seed": 10}},
        {"kind": "rollout", "tenant": "alpha", "request_id": "a-roll1",
         "params": {"n": 5, "ticks": ticks, "chunk_ticks": 20, "seed": 11,
                    "faults": {"dropout_frac": 0.4, "drop_tick": 15,
                               "rejoin_tick": 55}}},
        {"kind": "rollout", "tenant": "beta", "request_id": "b-roll0",
         "params": {"n": 8, "ticks": ticks, "chunk_ticks": 20, "seed": 20,
                    "faults": {"link_loss": 0.2}}},
        {"kind": "rollout", "tenant": "beta", "request_id": "b-roll1",
         "params": {"n": 8, "ticks": ticks, "chunk_ticks": 20,
                    "seed": 21}},
        {"kind": "assign", "tenant": "gamma", "request_id": "g-assign",
         "params": {"n": 16, "seed": 30}},
        {"kind": "gains", "tenant": "gamma", "request_id": "g-gains",
         "params": {"n": 5, "seed": 31}},
    ]
    if not quick:
        mix += [
            {"kind": "rollout", "tenant": "gamma",
             "request_id": "g-roll0",
             "params": {"n": 5, "ticks": ticks, "chunk_ticks": 20,
                        "seed": 32}},
            {"kind": "assign", "tenant": "beta", "request_id": "b-assign",
             "params": {"n": 16, "seed": 22, "solver": "lap"}},
        ]
    return mix


def _reference_digests(specs: list[dict]) -> dict[str, dict]:
    """Uncontended single-worker oracle for every rollout spec: final
    digest plus the per-chunk digest chain (a mismatch report that
    names the FIRST diverging chunk is evidence; a bare final-digest
    mismatch is just an alarm)."""
    from aclswarm_tpu.serve import ServiceConfig, SwarmService

    ref = SwarmService(ServiceConfig(max_batch=4))
    tickets = [(s["request_id"],
                ref.submit(s["kind"], s["params"], tenant=s["tenant"]))
               for s in specs]
    out = {}
    for rid, t in tickets:
        res = t.result(600)
        assert res.ok, f"reference run failed for {rid}"
        out[rid] = {"digest": int(res.value["digest"]),
                    "chunks": [int(d) for d
                               in res.value["chunk_digests"]]}
    ref.close()
    return out


def run_soak(out: str | None, quick: bool) -> int:
    from aclswarm_tpu.resilience import InjectedCrash, arm_many
    from aclswarm_tpu.resilience.crash import CrashPlan
    from aclswarm_tpu.serve import (ServiceConfig, SwarmService,
                                    bucket_of, place_slot)
    from aclswarm_tpu.serve.service import _read_frame

    t_start = time.time()
    problems: list[str] = []
    mix = request_mix(quick)
    roll_specs = [s for s in mix if s["kind"] == "rollout"]
    # reference FIRST: warms the in-process compile cache the soak
    # service reuses, so the kills land on execution, not compilation
    ref = _reference_digests(roll_specs)

    with tempfile.TemporaryDirectory(prefix="aclswarm_mw_soak_") as d:
        svc = SwarmService(ServiceConfig(
            workers=WORKERS, max_batch=2, quantum_chunks=1,
            max_queue_per_tenant=6, max_queue_total=24, journal_dir=d,
            supervise_poll_s=0.02, rejoin_base_s=0.05, rejoin_max_s=0.5,
            max_worker_restarts=8))

        def poison(params):
            raise InjectedCrash("poisoned request: kills its worker")

        svc.register("poison", poison)

        # repeated single-worker kills: target the slots that OWN the
        # two rollout buckets (rendezvous placement is deterministic),
        # each at a round with that bucket's work in flight; a second
        # kill on the n=5 slot after its respawn makes the kills
        # REPEATED on one slot, not just one-per-slot
        slots = list(range(WORKERS))
        slot5 = place_slot(bucket_of("rollout", roll_specs[0]["params"]),
                           slots)
        slot8 = place_slot(bucket_of("rollout", roll_specs[2]["params"]),
                           slots)
        plans = [CrashPlan(f"serve.w{slot5}", 2, "raise"),
                 CrashPlan(f"serve.w{slot5}", 5, "raise")]
        if slot8 != slot5:
            plans.append(CrashPlan(f"serve.w{slot8}", 3, "raise"))
        arm_many(plans)

        tickets = []
        for spec in mix:
            tickets.append((spec, svc.submit(
                spec["kind"], spec["params"], tenant=spec["tenant"],
                request_id=spec["request_id"])))
        # the poisoned request rides tenant gamma's queue mid-stream
        tickets.append((
            {"kind": "poison", "tenant": "gamma",
             "request_id": "g-poison"},
            svc.submit("poison", {}, tenant="gamma",
                       request_id="g-poison")))

        order: list[tuple[str, str]] = []      # (tenant, rid) by finish
        results = {}
        for spec, t in tickets:
            res = t.result(timeout=900)
            results[spec["request_id"]] = (spec, res)
        for spec, t in sorted(tickets,
                              key=lambda st: results[
                                  st[0]["request_id"]][1].latency_s):
            order.append((spec["tenant"], spec["request_id"]))
        arm_many([])
        stats = dict(svc.stats)
        svc.close()

        # ---- audit: ledger, losses, migration parity, poison, fairness
        accepted = len(tickets)
        statuses = {rid: res.status for rid, (_, res) in results.items()}
        completed = sum(1 for s in statuses.values() if s == "completed")
        timed_out = sum(1 for s in statuses.values() if s == "timed_out")
        failed = sum(1 for s in statuses.values() if s == "failed")
        silent = accepted - (completed + timed_out + failed)
        if silent:
            problems.append(f"{silent} request(s) without a terminal "
                            "status (SILENT LOSS)")
        # every accepted request must ALSO be terminal in the journal
        for reqf in Path(d).glob("req_*.req"):
            if not reqf.with_suffix(".done").exists():
                problems.append(
                    f"journal: {reqf.name} accepted but never terminal")

        pres = results["g-poison"][1]
        if pres.status != "failed" or pres.error.code != "poisoned":
            problems.append(
                "poisoned request did not terminate with the structured "
                f"poisoned error (got {pres.status}/"
                f"{pres.error.code if pres.error else None})")

        migrated = [rid for rid, (_, res) in results.items()
                    if res.ok and res.failovers > 0]
        mismatches = []
        for rid, want in ref.items():
            if statuses.get(rid) != "completed":
                continue
            res = results[rid][1]
            if int(res.value["digest"]) == want["digest"]:
                continue
            mismatches.append(rid)
            got_chain = [int(d) for d in res.value["chunk_digests"]]
            diverge = next(
                (i for i, (a, b) in enumerate(
                    zip(got_chain, want["chunks"])) if a != b),
                min(len(got_chain), len(want["chunks"])))
            problems.append(
                f"migrated/contended digest mismatch: {rid} "
                f"(first divergent chunk {diverge}; got "
                f"{len(got_chain)} chunks {[hex(d) for d in got_chain]}"
                f" vs ref {[hex(d) for d in want['chunks']]}; "
                f"failovers={res.failovers} "
                f"preemptions={res.preemptions} chunks={res.chunks})")
        migrated_rollouts = [r for r in migrated if r in ref]
        bit_identical = not mismatches and bool(ref)
        if not migrated_rollouts:
            problems.append("no rollout ever migrated workers — the "
                            "kills missed every in-flight batch")

        # fairness through failover: every tenant's first UNKILLED
        # completion within the first 2 x tenants terminals (poison
        # excluded). Requests that themselves rode a killed batch
        # (failovers > 0) are excluded from the index — their delay is
        # the kill's rejoin backoff, not scheduler starvation, and
        # since the PR-11 staged round made unkilled requests finish
        # in milliseconds, a kill-target tenant's whole stream would
        # otherwise sort last and fake a starvation signal. A tenant
        # whose ENTIRE clean stream migrated is judged by the
        # zero-loss ledger instead (it completed; its ordering is the
        # kill's doing).
        clean_order = [(t, r) for t, r in order if r != "g-poison"]
        first_idx = {}
        migrated_tenants = set()
        for i, (tenant, rid) in enumerate(clean_order):
            if results[rid][1].failovers == 0:
                first_idx.setdefault(tenant, i)
            else:
                migrated_tenants.add(tenant)
        # every tenant must be ACCOUNTED for: judged by its first
        # unkilled completion, or explained by having ridden a killed
        # batch — a tenant absent from both is starvation, and an
        # empty first_idx must never pass vacuously
        fairness_ok = (all(t in first_idx or t in migrated_tenants
                           for t in TENANTS)
                       and all(i < 2 * len(TENANTS)
                               for i in first_idx.values()))
        if not fairness_ok:
            problems.append(
                f"tenant starved during failover: first-completion "
                f"indices {first_idx} (kill-riding tenants: "
                f"{sorted(migrated_tenants)})")

        if stats["failovers"] < 3:
            problems.append(
                f"expected >= 3 worker kills (2 scripted + poison), "
                f"got failovers={stats['failovers']}")

        lat = sorted(res.latency_s for _, res in results.values())

    row = {
        "name": "serve_multiworker_soak",
        "n": 8,                       # largest rollout shape in the mix
        "backend": _backend(),
        "workers": WORKERS,
        "tenants": len(TENANTS),
        "accepted": accepted,
        "completed": completed,
        "rejected": int(stats["rejected"]),
        "preempted": int(stats["preempted"]),
        "timed_out": timed_out,
        "failed": failed,
        "poisoned": int(stats["poisoned"]),
        "silent_losses": int(silent),
        "worker_kills": int(stats["failovers"]),
        "requeued": int(stats["requeued"]),
        "migrated_resumes": len(migrated_rollouts),
        "migrated_bit_identical": bool(bit_identical
                                       and migrated_rollouts),
        "fairness_ok": bool(fairness_ok),
        "latency_s": {
            "p50": round(float(np.percentile(lat, 50)), 3),
            "p95": round(float(np.percentile(lat, 95)), 3),
            "p99": round(float(np.percentile(lat, 99)), 3),
        },
        "wall_s": round(time.time() - t_start, 1),
        "quick": bool(quick),
    }
    print(json.dumps(row, indent=1))
    if problems:
        print(f"SOAK FAILED ({len(problems)} broken promise(s)):")
        for p in problems:
            print(f"  {p}")
        return 1
    if out:
        p = Path(out)
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(json.dumps(row, indent=1) + "\n")
        print(f"wrote {p}")
    return 0


def _backend() -> str:
    import jax
    return jax.default_backend()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true",
                    help="smaller mix (CI smoke; artifact not committed)")
    ap.add_argument("--out",
                    default=str(RESULTS / "serve_multiworker_soak.json"),
                    help="artifact path ('' to skip writing)")
    args = ap.parse_args(argv)
    return run_soak(args.out or None, args.quick)


if __name__ == "__main__":
    sys.exit(main())
