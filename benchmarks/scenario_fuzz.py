"""Scenario fuzzer: random axis compositions vs the swarmcheck oracle.

The invariant registry (`aclswarm_tpu.analysis.invariants`) stops being
a passive sanitizer here and becomes an active bug-hunting harness: each
fuzz case composes a RANDOM subset of the scenario axes (obstacles,
wind, sensor noise, formation sequences, byzantine bidders, goal drift)
at random in-space strengths — optionally stacked on a random
FaultSchedule — and runs the batched engine with ``check_mode='on'``.
Any contract violation (a dead vehicle moving under wind, a corrupted
assignment that is not a permutation, a non-finite morph table, a
Sinkhorn marginal blowout on byzantine costs, an out-of-bounds blow-out)
fails the sweep with (seed, trial, tick, contract) attribution.

Heterogeneity is the point: every trial in a fuzz batch carries a
DIFFERENT composition inside ONE compiled vmapped scan — the same
one-program property the scenario subsystem promises the serve layer.

Run:
    python benchmarks/scenario_fuzz.py               # 50 seeds (the bar)
    python benchmarks/scenario_fuzz.py --seeds 8     # smoke (check.sh)

Exit 0 = zero violations. Exit 1 names every violating case.
"""
from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# per-axis fuzz spaces (mirrors the registry families' documented
# ranges — in-space compositions are the zero-violation contract;
# see aclswarm_tpu/scenarios/registry.py for the envelope rationale)
AXIS_SPACES = {
    "obstacles": lambda rng: dict(
        count=int(rng.integers(1, 5)),   # inclusive of the K=4 cap —
        #                                  the all-slots-active boundary
        radius=float(rng.uniform(0.5, 1.5)),
        speed=float(rng.choice([0.0, rng.uniform(0.2, 0.6)])),
        appear_frac=float(rng.uniform(0.1, 0.4)),
        vanish_frac=float(rng.uniform(0.5, 1.0))),
    "wind": lambda rng: dict(
        wind=float(rng.uniform(0.05, 0.25)),
        gust=float(rng.uniform(0.0, 0.05)),
        onset_frac=float(rng.uniform(0.0, 0.5))),
    "noise": lambda rng: dict(
        sigma=float(rng.uniform(0.05, 0.3)),
        onset_frac=float(rng.uniform(0.0, 0.5))),
    "sequence": lambda rng: dict(
        stages=int(rng.integers(1, 3)),
        split=bool(rng.integers(0, 2))),
    "byzantine": lambda rng: dict(
        frac=float(rng.uniform(0.1, 0.3)),
        sigma=float(rng.uniform(0.5, 3.0)),
        onset_frac=float(rng.uniform(0.0, 0.5))),
    "drift": lambda rng: dict(
        speed=float(rng.uniform(0.02, 0.1)),
        onset_frac=float(rng.uniform(0.0, 0.5)),
        rematch_every=int(rng.choice([0, 120, 240]))),
}


def _composition(rng: np.random.Generator, flooded: bool) -> dict:
    """One random axis composition (>= 1 axis; noise only bites — and
    is only scripted — under the flooded information model)."""
    axes = [a for a in AXIS_SPACES if a != "noise" or flooded]
    picked = [a for a in axes if rng.random() < 0.5]
    if not picked:
        picked = [axes[int(rng.integers(0, len(axes)))]]
    return {a: AXIS_SPACES[a](rng) for a in picked}


def run_fuzz(seeds: int = 50, *, n: int = 8, ticks: int = 480,
             batch: int = 4, seed0: int = 0,
             verbose: bool = True) -> list[dict]:
    """Sweep ``seeds`` random compositions in batches of ``batch``
    heterogeneous trials; returns a list of violation records (empty =
    the oracle stayed silent)."""
    import jax
    import jax.numpy as jnp

    from aclswarm_tpu import faults, scenarios as scn, sim
    from aclswarm_tpu.analysis import invariants as invlib
    from aclswarm_tpu.core.types import (ControlGains, SafetyParams,
                                         make_formation)

    dt = jnp.result_type(float)
    sparams = SafetyParams(
        bounds_min=jnp.asarray([-50.0, -50.0, 0.0], dt),
        bounds_max=jnp.asarray([50.0, 50.0, 10.0], dt))
    ang = np.linspace(0, 2 * np.pi, n, endpoint=False)
    r = scn.registry.formation_scale(n)
    pts = np.stack([r * np.cos(ang), r * np.sin(ang),
                    np.full(n, 2.0)], 1)
    form = make_formation(jnp.asarray(pts, dt),
                          jnp.asarray(np.ones((n, n)) - np.eye(n), dt))

    violations: list[dict] = []
    case = 0
    while case < seeds:
        bsz = min(batch, seeds - case)
        meta_rng = np.random.default_rng(seed0 + 7_000_003 + case)
        # batch-shared engine knobs (one compiled config per batch):
        # solver x information model x fault presence all rotate
        solver = str(meta_rng.choice(["auction", "sinkhorn", "cbaa"]))
        flooded = bool(meta_rng.integers(0, 2))
        with_faults = meta_rng.random() < 0.4
        cfg = sim.SimConfig(assignment=solver, assign_every=40,
                            localization="flooded" if flooded else
                            "truth", check_mode="on")
        comps, states = [], []
        for b in range(bsz):
            s = seed0 + case + b
            rng = np.random.default_rng(s)
            parts = _composition(rng, flooded)
            comps.append(sorted(parts))
            scen = scn.compose(n, s, parts, dtype=dt, horizon=ticks)
            fs = None
            if with_faults:
                fs = faults.sample_schedule(
                    s, n, dropout_frac=float(rng.uniform(0, 0.3)),
                    drop_tick=int(ticks * 0.25),
                    rejoin_tick=int(ticks * 0.6),
                    link_loss=float(rng.uniform(0, 0.3)), dtype=dt)
            q0 = rng.normal(size=(n, 3)) * (0.4 * r)
            q0[:, 2] = 2.0 + rng.normal(size=n) * 0.2
            states.append(sim.init_state(
                jnp.asarray(q0, dt), localization=flooded, faults=fs,
                checks=True, scenario=scen))
        bstate = jax.tree.map(lambda *xs: jnp.stack(xs), *states)
        bform = jax.tree.map(lambda *xs: jnp.stack(xs), *([form] * bsz))
        t0 = time.time()
        _, metrics = sim.batched_rollout(bstate, bform, ControlGains(),
                                         sparams, cfg, ticks)
        codes = np.asarray(metrics.inv_code)        # (ticks, bsz)
        for b in range(bsz):
            hit = invlib.first_violation(codes[:, b])
            tag = (f"seed {seed0 + case + b} [{solver}"
                   f"{'/flooded' if flooded else ''}"
                   f"{'/faults' if with_faults else ''}] "
                   f"axes={'+'.join(comps[b])}")
            if hit is None:
                if verbose:
                    print(f"ok   {tag}", flush=True)
                continue
            tick, contract = hit
            violations.append(dict(seed=seed0 + case + b, trial=b,
                                   tick=tick, contract=contract.id,
                                   solver=solver, flooded=flooded,
                                   faults=with_faults,
                                   axes=comps[b]))
            print(f"VIOLATION {tag}: {contract.id} at tick {tick}",
                  flush=True)
        if verbose:
            print(f"  batch of {bsz} in {time.time() - t0:.1f}s",
                  flush=True)
        case += bsz
    return violations


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seeds", type=int, default=50,
                    help="fuzz cases to sweep (acceptance bar: >= 50)")
    ap.add_argument("--n", type=int, default=8)
    ap.add_argument("--ticks", type=int, default=480)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seed0", type=int, default=0)
    ap.add_argument("-q", "--quiet", action="store_true")
    args = ap.parse_args(argv)

    t0 = time.time()
    bad = run_fuzz(args.seeds, n=args.n, ticks=args.ticks,
                   batch=args.batch, seed0=args.seed0,
                   verbose=not args.quiet)
    wall = time.time() - t0
    if bad:
        print(f"FUZZ FAILED: {len(bad)}/{args.seeds} compositions "
              f"violated invariants ({wall:.0f}s):")
        for v in bad:
            print(f"  {v}")
        return 1
    print(f"fuzz clean: {args.seeds} random axis compositions, "
          f"swarmcheck on, zero violations ({wall:.0f}s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
