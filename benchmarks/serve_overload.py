"""serve_overload — the load-vs-SLO surface for the TCP front end,
with zero silent losses proven AT 10x OVERLOAD (ROADMAP open item 3;
docs/SERVICE.md §off-host serving).

The question this artifact answers: when the offered load, the
clients, and the network are all hostile, does admission control SHED
load with honest ``retry_after`` hints — goodput held, rejects
explicit, every accepted request still terminating attributably — or
does the service collapse? The committed surface sweeps offered load
from 0.5x to 10x of the measured capacity, each level driven by the
open-loop adversarial traffic fleet (`aclswarm_tpu.serve.traffic`:
heavy-tailed arrivals, skewed tenants, scenario-registry request
mixes, deadline distributions, a slow-loris client, a corrupt-frame
client, kill/reconnect storms) against a JOURNALED service behind the
TCP wire server.

Per level the row reports goodput (terminal completions/s), p50/p99
accept->terminal latency, the reject ledger (server rejections,
arrivals shed after their bounded hint-honoring retries,
accepted-after-retry — the retry_after honesty evidence), and the
zero-silent-loss audit: every accepted request must have a terminal
done-frame in the journal, and `telemetry.postmortem` must attribute
every one (the disputed-request escrow — `--request-id` any of them).

Acceptance bars, enforced AS SCHEMA by
`benchmarks/check_results.py::check_serve_overload`:

- >= 4 committed offered-load levels, the highest >= 10x capacity;
- ``silent_losses == 0`` on every row;
- goodput at 10x >= 90% of goodput at 1x (shedding, not collapsing);
- rejects > 0 at 10x (the shed is real, not a mis-measured capacity).

Run:

    JAX_PLATFORMS=cpu python benchmarks/serve_overload.py [--quick] \
        [--out benchmarks/results/serve_overload.json]
    JAX_PLATFORMS=cpu python benchmarks/serve_overload.py --smoke
        # the 30 s CI gate: ONLY the 10x level, journaled, postmortem
        # attribution, exit 1 on any silent loss (scripts/check.sh)
"""
from __future__ import annotations

import argparse
import json
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

RESULTS = Path(__file__).resolve().parent / "results"

# the committed sweep: offered load as multiples of measured capacity
MULTIPLIERS = (0.5, 1.0, 2.0, 10.0)
MULTIPLIERS_QUICK = (0.5, 10.0)
DURATION_S = 6.0
DURATION_S_QUICK = 2.5
N = 5

# one service shape for calibration and every level: modest bounded
# queues (admission must visibly shed at 10x), staged rounds, 4-slot
# batches — the serve_throughput posture plus a journal
SERVICE_KW = dict(max_batch=4, quantum_chunks=4,
                  max_queue_per_tenant=16, max_queue_total=48,
                  idle_poll_s=0.01)


def _service(journal: str | None):
    from aclswarm_tpu.serve import ServiceConfig, SwarmService
    return SwarmService(ServiceConfig(journal_dir=journal, **SERVICE_KW))


def _traffic_cfg(offered_hz: float, duration_s: float, seed: int,
                 adversaries: bool = True, reject_retries: int = 2):
    from aclswarm_tpu.serve.traffic import TrafficConfig
    return TrafficConfig(
        seed=seed, duration_s=duration_s, offered_hz=offered_hz,
        reject_retries=reject_retries, max_retry_wait_s=8.0,
        slowloris_clients=1 if adversaries else 0,
        corrupt_clients=1 if adversaries else 0,
        reconnect_storms=2 if adversaries else 0,
        storm_period_s=max(1.0, duration_s / 3.0),
        drain_timeout_s=240.0)


def _warmup() -> str:
    """Compile every shape the levels reach (rollout batches at the
    pow2 sizes, the scenario-general staging ops, assign) outside the
    measured windows — a level must measure the scheduler, not the
    compiler."""
    import jax

    from aclswarm_tpu.serve.traffic import _serve_families

    fams = _serve_families()
    for b in (1, 2, 4):
        svc = _service(None)
        tickets = [svc.submit("rollout",
                              {"n": N, "ticks": 60, "chunk_ticks": 20,
                               "seed": 100 * b + i})
                   for i in range(b)]
        tickets.append(svc.submit("assign", {"n": N, "seed": b}))
        if fams:
            tickets.append(svc.submit(
                "scenario", {"n": N, "ticks": 60, "chunk_ticks": 20,
                             "seed": b, "family": fams[b % len(fams)]}))
        for t in tickets:
            res = t.result(timeout=600)
            assert res.ok, f"warmup (b={b}) failed: {res}"
        svc.close()
    return jax.default_backend()


def _run_level(offered_hz: float, duration_s: float, seed: int,
               adversaries: bool = True, reject_retries: int = 2
               ) -> dict:
    """One offered-load level: journaled service + TCP wire server +
    the adversarial fleet, then the journal audit. Returns the merged
    fleet report + audit fields."""
    from aclswarm_tpu.serve.traffic import TrafficFleet
    from aclswarm_tpu.serve.wire import WireServer
    from aclswarm_tpu.telemetry import postmortem

    with tempfile.TemporaryDirectory(prefix="aclswarm_overload_") as jd:
        svc = _service(jd)
        srv = WireServer(svc, base=None, tcp=("127.0.0.1", 0),
                         client_lease_s=8.0, read_deadline_s=2.0,
                         handshake_s=2.0)
        host, port = srv.tcp_address
        cfg = _traffic_cfg(offered_hz, duration_s, seed, adversaries,
                           reject_retries)
        fleet = TrafficFleet(cfg, host, port)
        t0 = time.perf_counter()
        rep = fleet.run()
        srv.close()
        svc.close(drain=True, timeout=120.0)
        wall = time.perf_counter() - t0
        stats = dict(svc.stats)
        tel = svc.telemetry

        # ---- the zero-silent-loss audit, from DISK alone -------------
        # every accepted request (req-frame) must be terminal
        # (done-frame); anything else is a silent loss. The postmortem
        # must also attribute every accepted request's timeline — the
        # disputed-request escrow.
        jd_path = Path(jd)
        accepted_rids = {p.name[len("req_"):-len(".req")]
                         for p in jd_path.glob("req_*.req")}
        done_rids = {p.name[len("req_"):-len(".done")]
                     for p in jd_path.glob("req_*.done")}
        silent = sorted(accepted_rids - done_rids)
        pm = postmortem.reconstruct(jd)
        rep.update({
            "offered_hz": offered_hz,
            "accepted": len(accepted_rids),
            "silent_losses": len(silent),
            "silent_rids": silent[:8],
            "pm_reconstructed": pm["reconstructed"],
            "pm_complete": pm["complete"],
            "server_rejected": stats["rejected"],
            "server_completed": stats["completed"],
            "crc_rejected": int(
                tel.counter("wire_crc_rejected_total").value),
            "slowloris_dropped": int(
                tel.counter("wire_slowloris_dropped_total").value),
            "reconnects": int(
                tel.counter("wire_reconnects_total").value),
            "level_wall_s": wall,
        })
        return rep


def _row(rep: dict, mult: float, capacity_hz: float, backend: str,
         quick: bool) -> dict:
    goodput = (rep["completed"] / rep["wall_s"]) if rep["wall_s"] else 0.0
    shed = rep["rejected_final"]
    return {
        "name": "serve_overload",
        "level": f"{mult:g}x",
        "multiplier": mult,
        "n": N,
        "backend": backend,
        "capacity_hz": round(capacity_hz, 3),
        "offered_hz": round(rep["offered_hz"], 3),
        "value": round(goodput, 3),
        "unit": "Hz",
        "p50_s": round(rep["latency_p50_s"], 4),
        "p99_s": round(rep["latency_p99_s"], 4),
        "offered": rep["offered"],
        "accepted": rep["accepted"],
        "completed": rep["completed"],
        "timed_out": rep["timed_out"],
        "cancelled": rep["cancelled"],
        "shed": shed,
        "wire_lost": rep["wire_lost"],
        "failed_other": rep["failed_other"],
        "reject_rate": round(shed / max(1, rep["offered"]), 4),
        "server_rejected": rep["server_rejected"],
        "retry_submits": rep["retry_submits"],
        "accepted_after_retry": rep["accepted_after_retry"],
        "retry_after_p50": round(rep["retry_after_p50"], 3),
        "silent_losses": rep["silent_losses"],
        "pm_complete": rep["pm_complete"],
        "pm_reconstructed": rep["pm_reconstructed"],
        "crc_rejected": rep["crc_rejected"],
        "slowloris_dropped": rep["slowloris_dropped"],
        "reconnects": rep["reconnects"],
        "unresolved": rep["unresolved"],
        "wall_s": round(rep["wall_s"], 2),
        "quick": quick,
    }


def calibrate(duration_s: float = 3.0) -> float:
    """Measured capacity: completed/s under a saturating (way-past-
    capacity) polite open-loop burst against the SAME service shape
    the levels use. The multipliers anchor here, so 10x means 10x of
    what this host actually drains."""
    # no hint-honoring retries here: the retry tail would stretch the
    # wall past the saturated window and undersell capacity — the
    # anchor is the polite-saturation drain rate
    rep = _run_level(1200.0, duration_s, seed=99, adversaries=False,
                     reject_retries=0)
    cap = rep["completed"] / rep["wall_s"]
    print(f"calibrated capacity: {cap:.1f} req/s "
          f"({rep['completed']} completed / {rep['wall_s']:.1f} s)",
          flush=True)
    return cap


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true",
                    help="2 short levels (CI smoke; artifact not "
                         "committed)")
    ap.add_argument("--smoke", action="store_true",
                    help="the ~30 s check.sh gate: only the 10x level, "
                         "assert zero silent losses via the journal + "
                         "postmortem; no artifact")
    ap.add_argument("--seed", type=int, default=20)
    ap.add_argument("--out", default=None,
                    help="artifact path ('' to skip writing; default: "
                         "the committed artifact for full runs, NO "
                         "write for --quick — a quick smoke must not "
                         "clobber the committed 4-level surface)")
    args = ap.parse_args(argv)
    if args.out is None:
        args.out = "" if args.quick \
            else str(RESULTS / "serve_overload.json")

    backend = _warmup()
    if args.smoke:
        cap = calibrate(1.5)
        rep = _run_level(10.0 * cap, 3.0, seed=args.seed)
        ok = (rep["silent_losses"] == 0 and rep["unresolved"] == 0
              and rep["pm_complete"] == rep["pm_reconstructed"])
        print(json.dumps({k: rep[k] for k in
                          ("offered", "accepted", "completed",
                           "timed_out", "cancelled", "rejected_final",
                           "silent_losses", "pm_reconstructed",
                           "pm_complete", "unresolved", "crc_rejected",
                           "slowloris_dropped", "reconnects")},
                         indent=1))
        if not ok:
            print("FAIL: overload smoke found silent losses or "
                  f"unattributable requests (silent={rep['silent_rids']})")
            return 1
        print(f"PASS: 10x overload ({10 * cap:.0f} req/s offered vs "
              f"{cap:.0f} capacity), {rep['accepted']} accepted, 0 "
              "silent losses, every request journal-attributable")
        return 0

    mults = MULTIPLIERS_QUICK if args.quick else MULTIPLIERS
    dur = DURATION_S_QUICK if args.quick else DURATION_S
    cap = calibrate(1.5 if args.quick else 3.0)
    rows = []
    broken = 0
    for k, mult in enumerate(mults):
        rep = _run_level(mult * cap, dur, seed=args.seed + k)
        row = _row(rep, mult, cap, backend, bool(args.quick))
        rows.append(row)
        print(json.dumps(row), flush=True)
        broken += rep["silent_losses"] + rep["unresolved"]
    if broken:
        print(f"FAIL: {broken} silent loss(es)/unresolved request(s)")
        return 1
    if args.out:
        p = Path(args.out)
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
        print(f"wrote {p}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
