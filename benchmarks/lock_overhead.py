"""swarmguard lock-tier tax measurement (docs/OBSERVABILITY.md;
acceptance bar: OrderedLock < 2% of serve-round wall).

The fleet's host-side locks are `aclswarm_tpu.utils.locks.OrderedLock`
— rank-checked when ACLSWARM_LOCK_DEBUG=1, and always feeding
lock_hold_s/lock_wait_s histograms when constructed with a registry.
That discipline must be effectively free in production (disarmed):
this benchmark serves the same mixed request set the serve smoke uses
through a real `SwarmService`, once with the shipped OrderedLock and
once with plain `threading.Lock` patched into every adopting module,
and reports the median relative wall overhead. Two microbench keys
ride along: the uncontended acquire/release pair cost disarmed and
armed (the armed cost is the debug-mode price, not a production bar).

Run:

    JAX_PLATFORMS=cpu python benchmarks/lock_overhead.py \
        [--out benchmarks/results/lock_overhead.json]

Rows are schema-guarded by `benchmarks/check_results.py
::check_lock_overhead` (exact key set, the < 2% bar enforced on the
committed artifact).
"""
from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

RESULTS = Path(__file__).resolve().parent / "results"

REQUESTS = [
    ("rollout", {"n": 5, "ticks": 80, "chunk_ticks": 20, "seed": 11}),
    ("assign", {"n": 12, "seed": 3}),
    ("gains", {"n": 5, "seed": 0}),
]


def _plain_lock(family, *, rank=None, registry=None, name=None):
    """Ctor-compatible stand-in: the pre-swarmguard locking."""
    return threading.Lock()


def _serve_round(svc_cls, cfg) -> float:
    svc = svc_cls(cfg)
    try:
        t0 = time.perf_counter()
        tickets = [svc.submit(kind, dict(params, seed=i),
                              request_id=f"lock-bench-{kind}-{i}")
                   for i, (kind, params) in enumerate(REQUESTS * 2)]
        for t in tickets:
            res = t.result(timeout=300)
            assert res.ok, res
        return time.perf_counter() - t0
    finally:
        svc.close()


def run_overhead(out: str | None, reps: int = 5) -> int:
    from aclswarm_tpu.serve import ServiceConfig, SwarmService
    from aclswarm_tpu.serve import service as svcmod
    from aclswarm_tpu.serve import workers as wrkmod
    from aclswarm_tpu.telemetry import registry as regmod
    from aclswarm_tpu.utils import locks as locklib

    cfg = ServiceConfig(max_batch=2)
    patchees = [svcmod, wrkmod, regmod]

    # warm the compile caches (shared per-process) outside timing
    _serve_round(SwarmService, cfg)

    ordered, plain = [], []
    for _ in range(reps):
        saved = [m.OrderedLock for m in patchees]
        try:
            for m in patchees:
                m.OrderedLock = _plain_lock
            plain.append(_serve_round(SwarmService, cfg))
        finally:
            for m, orig in zip(patchees, saved):
                m.OrderedLock = orig
        ordered.append(_serve_round(SwarmService, cfg))
    plain_s = float(np.median(plain))
    ordered_s = float(np.median(ordered))
    frac = max(0.0, ordered_s / plain_s - 1.0)

    # microbench: uncontended acquire/release pair, disarmed vs armed
    # vs threading.Lock (no registry — the pure discipline cost)
    k = 200_000

    def _pairs(lk) -> float:
        t0 = time.perf_counter()
        for _ in range(k):
            with lk:
                pass
        return (time.perf_counter() - t0) / k * 1e9

    plain_ns = _pairs(threading.Lock())
    pair_ns = _pairs(locklib.OrderedLock("bench.micro"))
    locklib.arm()
    try:
        armed_ns = _pairs(locklib.OrderedLock("bench.micro.armed"))
    finally:
        locklib.disarm()

    rows = [
        {"name": "lock_overhead_frac_serve", "n": len(REQUESTS) * 2,
         "value": round(frac, 4), "unit": "ratio",
         "wall_plain_s": round(plain_s, 3),
         "wall_ordered_s": round(ordered_s, 3), "reps": reps,
         "note": "SwarmService mixed-request round (smoke request set "
                 "x2, max_batch=2), shipped OrderedLock (disarmed, "
                 "hold/wait histograms on) vs threading.Lock patched "
                 "into service/workers/registry; acceptance < 0.02"},
        {"name": "lock_pair_ns", "n": k, "value": round(pair_ns, 1),
         "unit": "ns", "plain_pair_ns": round(plain_ns, 1),
         "armed_pair_ns": round(armed_ns, 1),
         "note": "uncontended acquire/release pair, OrderedLock "
                 "without a registry, detector disarmed; plain_pair_ns "
                 "is threading.Lock, armed_pair_ns the "
                 "ACLSWARM_LOCK_DEBUG=1 debug-mode price"},
    ]
    for r in rows:
        print(json.dumps(r), flush=True)
    if frac >= 0.02:
        print(f"FAIL: lock-tier overhead {frac:.1%} >= 2% acceptance "
              "bar")
        return 1
    if out:
        p = Path(out)
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
        print(f"wrote {p}")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=str(RESULTS / "lock_overhead.json"),
                    help="artifact path ('' to skip writing)")
    ap.add_argument("--reps", type=int, default=5)
    args = ap.parse_args(argv)
    return run_overhead(args.out or None, reps=args.reps)


if __name__ == "__main__":
    sys.exit(main())
