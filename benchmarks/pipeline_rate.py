"""Warm-started pipeline rate: the ROADMAP item 1 headline artifact.

Composes the four dispatch-loop stages — flooded localization + control
tick, cadenced assignment, and amortized (warm-started) ADMM gain
design — into sustained pipeline rows:

- ``admm_warm_start``: warm-vs-cold ADMM on a NEW formation seeded from
  the previous formation's fixed point (`gains.AdmmCarry`, the dispatch
  idiom `harness.trials` now threads). The acceptance bar — warm >= 3x
  fewer iterations than cold — is enforced as schema by
  `check_results.check_pipeline_n1000`.
- ``assign_churn``: the churn/lag trade curve under the PR-12
  `goal_drift` + `rematch_every` scenario, sweeping the `assign_eps`
  hysteresis (now applied inside CBAA itself) with warm `CbaaTables`
  carried across auctions. The eps=0 / no-warm run is compared BITWISE
  against the default-config engine (`baseline_parity`) — the
  zero-cost-off proof at artifact level.
- ``pipeline_rate``: sustained host-measured loops (mode='host') that
  run rollout chunks + cadenced assignment + dispatch-cadence gain
  redesign under one wall clock, and device-composed rows
  (mode='composed', the `scale_tpu.json` stage-rate idiom) that
  combine the committed n=1000 stage rates with the measured warm
  iteration fraction into the headline `pipeline_n1000_hz` row.

Methodology notes: host rows time a warmed-up loop (compile + first
solve excluded) and report per-stage attribution (`stage_ms`) next to
the sustained rate; composed rows do arithmetic on COMMITTED device
stage rates and say so (`gains_source`), never passing composition off
as measurement. On hosts that cannot run the n=1000 ADMM (single-core
CPU: minutes per eigh(3992) iteration), the n=1000 host row measures
ticks + assignment and composes only the gain term, with the source
recorded in the row.

Run: python benchmarks/pipeline_rate.py [--quick]
     [--out benchmarks/results/pipeline_n1000.json]
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

RESULTS = Path(__file__).resolve().parent / "results"
SCALE_TPU = RESULTS / "scale_tpu.json"

# the composed pipeline's cadences: auctions every 1.2 s
# (`coordination.launch:23` via SimConfig.assign_every=120) and a gain
# redesign per formation dispatch, one dispatch per 1.2 s as well (the
# trials drivers' fastest measured cycle at n=1000)
ASSIGN_EVERY = 120
REDESIGN_EVERY = 120


def _round6(x) -> float:
    return float(np.round(float(x), 6))


def _circle_formation(n: int, seed: int, radius: float | None = None,
                      jitter: float = 0.35):
    """A full-graph, non-planar formation with >= 1 m spacing — the fc
    dispatch shape (zero non-edges, 1-slot constraint bucket)."""
    rng = np.random.default_rng(seed)
    radius = radius or max(4.0, n / (2 * np.pi))
    ang = np.linspace(0, 2 * np.pi, n, endpoint=False)
    pts = np.stack([radius * np.cos(ang), radius * np.sin(ang),
                    2.0 + jitter * rng.standard_normal(n)], axis=1)
    adj = np.ones((n, n)) - np.eye(n)
    return pts, adj


def admm_warm_rows(n: int, reps: int, quick: bool) -> list[dict]:
    """Warm-vs-cold ADMM across DISTINCT formations: solve formation A,
    carry its fixed point into formation B's solve — exactly what a
    dispatch cycle does."""
    import jax.numpy as jnp

    from aclswarm_tpu import gains as gainslib

    pts_a, adj = _circle_formation(n, seed=11)
    pts_b, _ = _circle_formation(n, seed=12)

    # cold solve of B: iterations + median wall
    g_cold, st_cold = gainslib.solve_gains(pts_b, adj, max_nonedges=1,
                                           telemetry=True)
    np.asarray(g_cold)
    cold_t = []
    for _ in range(reps):
        t0 = time.monotonic()
        g, st = gainslib.solve_gains(pts_b, adj, max_nonedges=1,
                                     telemetry=True)
        np.asarray(g)
        cold_t.append(time.monotonic() - t0)

    # warm solve of B seeded from A's fixed point
    carry0 = gainslib.init_carry(n, gainslib.planar_of(pts_a))
    _, carry_a = gainslib.solve_gains(pts_a, adj, max_nonedges=1,
                                      carry=carry0)
    g_w, _, st_warm = gainslib.solve_gains(pts_b, adj, max_nonedges=1,
                                           carry=carry_a, telemetry=True)
    np.asarray(g_w)
    warm_t = []
    for _ in range(reps):
        t0 = time.monotonic()
        g, _, st = gainslib.solve_gains(pts_b, adj, max_nonedges=1,
                                        carry=carry_a, telemetry=True)
        np.asarray(g)
        warm_t.append(time.monotonic() - t0)

    cold_ms = _round6(1e3 * float(np.median(cold_t)))
    warm_ms = _round6(1e3 * float(np.median(warm_t)))
    gains_diff = float(jnp.max(jnp.abs(g_w - g_cold)))
    row = {
        "name": "admm_warm_start", "n": n,
        "backend": "cpu",
        "cold_iters": int(st_cold.iters), "warm_iters": int(st_warm.iters),
        "iters_speedup": _round6(st_cold.iters / max(st_warm.iters, 1)),
        "cold_ms": cold_ms, "warm_ms": warm_ms,
        "time_speedup": _round6(cold_ms / max(warm_ms, 1e-9)),
        # warm and cold land on the same fixed point to the ADMM's own
        # stopping tolerance (tests pin this at 5e-3)
        "gains_maxdiff": _round6(gains_diff),
        "quick": quick,
    }
    return [row]


def churn_rows(n: int, ticks: int, quick: bool) -> list[dict]:
    """The churn/lag trade curve: CBAA + warm tables under `goal_drift`,
    sweeping `assign_eps`; plus the eps=0 / no-warm bitwise parity row."""
    import jax
    import jax.numpy as jnp

    from aclswarm_tpu import sim
    from aclswarm_tpu.core import geometry
    from aclswarm_tpu.core import perm as permutil
    from aclswarm_tpu.core.types import ControlGains, SafetyParams, \
        make_formation
    from aclswarm_tpu.scenarios import registry as scenreg

    pts, adj = _circle_formation(n, seed=21)
    from aclswarm_tpu import gains as gainslib
    g = gainslib.solve_gains(pts, adj, max_nonedges=1)
    f = make_formation(pts, adj, np.asarray(g))
    sp = SafetyParams(
        bounds_min=jnp.asarray([-200.0, -200.0, 0.0]),
        bounds_max=jnp.asarray([200.0, 200.0, 50.0]))
    rng = np.random.default_rng(3)
    q0 = pts + rng.normal(scale=1.5, size=(n, 3)) * [1, 1, 0.2]
    q0[:, 2] = np.maximum(q0[:, 2], 0.5)

    assign_every, rematch_every, speed = 30, 60, 0.08
    scen = scenreg.sample("goal_drift", seed=5, n=n, horizon=ticks,
                          params={"drift.speed": speed,
                                  "drift.rematch_every": rematch_every})
    drift_vel = np.asarray(scen.drift_vel)
    drift_tick = int(scen.drift_tick)

    def run(eps: float, warm_tables: bool, default_cfg: bool = False):
        cfg = (sim.SimConfig(assignment="cbaa",
                             assign_every=assign_every) if default_cfg
               else sim.SimConfig(assignment="cbaa",
                                  assign_every=assign_every,
                                  assign_eps=eps))
        st = sim.init_state(q0, scenario=scen, cbaa_warm=warm_tables)
        final, m = sim.rollout(st, f, ControlGains(), sp, cfg, ticks)
        return final, jax.tree.map(np.asarray, m)

    def lag_cost(m) -> float:
        """Mean post-onset shape RMS against the DRIFTED formation,
        through the current assignment — the price of stale matches."""
        errs = []
        for t in range(drift_tick, ticks, assign_every):
            pts_t = pts + drift_vel * ((t - drift_tick) * 0.01)
            q_form = np.asarray(permutil.veh_to_formation_order(
                jnp.asarray(m.q[t]), jnp.asarray(m.v2f[t])))
            aligned = np.asarray(geometry.align(
                jnp.asarray(pts_t), jnp.asarray(q_form), d=2))
            resid = q_form - aligned
            resid[:, 2] -= resid[:, 2].mean()
            errs.append(float(np.sqrt(np.mean(np.sum(resid ** 2, -1)))))
        return float(np.mean(errs))

    def counts(m):
        auctions = int(np.sum(m.auctioned & m.assign_valid))
        reass = int(np.sum(m.reassigned))
        return auctions, reass

    rows = []
    # bitwise parity: eps=0.0 spelled out vs the default config — the
    # knob's off position IS today's engine
    _, m_base = run(0.0, warm_tables=False, default_cfg=True)
    _, m_off = run(0.0, warm_tables=False)
    parity = (bool(np.array_equal(m_base.q, m_off.q))
              and bool(np.array_equal(m_base.v2f, m_off.v2f))
              and bool(np.array_equal(m_base.reassigned, m_off.reassigned)))
    auctions, reass = counts(m_off)
    rows.append({
        "name": "assign_churn", "n": n, "assignment": "cbaa",
        "warm_tables": False, "assign_eps": 0.0,
        "assign_every": assign_every, "rematch_every": rematch_every,
        "drift_speed": speed, "ticks": ticks,
        "auctions": auctions, "reassigns": reass,
        "churn_rate": _round6(reass / max(auctions, 1)),
        "lag_rms_m": _round6(lag_cost(m_off)),
        "baseline_parity": parity, "quick": quick,
    })
    for eps in (0.0, 0.05, 0.1, 0.2):
        _, m = run(eps, warm_tables=True)
        auctions, reass = counts(m)
        rows.append({
            "name": "assign_churn", "n": n, "assignment": "cbaa",
            "warm_tables": True, "assign_eps": eps,
            "assign_every": assign_every, "rematch_every": rematch_every,
            "drift_speed": speed, "ticks": ticks,
            "auctions": auctions, "reassigns": reass,
            "churn_rate": _round6(reass / max(auctions, 1)),
            "lag_rms_m": _round6(lag_cost(m)),
            "baseline_parity": False, "quick": quick,
        })
    return rows


def _pipeline_row(*, n, mode, backend, assignment, assign_every,
                  redesign_every, ticks, warm_gains, tick_ms, assign_ms,
                  gains_ms, gains_source, measured_hz, quick) -> dict:
    """One `pipeline_rate` row; the exact key set the checker enforces.
    `value` is the full-pipeline sustained rate — measured wall when
    every stage ran on the host (gains_source='measured'), otherwise
    measured ticks+assign with the amortized composed gain term added
    (gains_source names the artifact it came from)."""
    per_tick_ms = (tick_ms + assign_ms / assign_every
                   + gains_ms / redesign_every)
    return {
        "name": "pipeline_rate", "n": n, "mode": mode, "backend": backend,
        "assignment": assignment, "assign_every": assign_every,
        "redesign_every": redesign_every, "ticks": ticks,
        "warm_gains": warm_gains,
        "tick_ms": _round6(tick_ms),
        "stage_ms": {"tick": _round6(tick_ms),
                     "assign": _round6(assign_ms),
                     "gains": _round6(gains_ms)},
        "gains_source": gains_source,
        "value": _round6(measured_hz if measured_hz is not None
                         else 1e3 / per_tick_ms),
        "unit": "Hz", "quick": quick,
    }


def host_pipeline_rows(n: int, ticks: int, chunk: int, quick: bool,
                       warm_frac: float) -> list[dict]:
    """Sustained host loop: flooded rollout chunks + cadenced Sinkhorn
    (inside the rollout) + dispatch-cadence ADMM redesign between
    chunks, one wall clock over everything after warm-up."""
    import jax.numpy as jnp

    from aclswarm_tpu import gains as gainslib
    from aclswarm_tpu import sim
    from aclswarm_tpu.core.types import ControlGains, SafetyParams, \
        make_formation

    assign_every = min(ASSIGN_EVERY, max(chunk // 2, 2))
    redesign_every = max(chunk, REDESIGN_EVERY)
    pts, adj = _circle_formation(n, seed=31)
    run_gains = n < 1000   # single-core hosts cannot eigh(3992)

    carry = gainslib.init_carry(n, gainslib.planar_of(pts))
    if run_gains:
        g, carry = gainslib.solve_gains(pts, adj, max_nonedges=1,
                                        carry=carry)
        g = np.asarray(g)
    else:
        g = np.zeros((3 * n, 3 * n))
    f = make_formation(pts, adj, g)
    sp = SafetyParams(
        bounds_min=jnp.asarray([-500.0, -500.0, 0.0]),
        bounds_max=jnp.asarray([500.0, 500.0, 100.0]))
    rng = np.random.default_rng(7)
    q0 = pts + rng.normal(scale=1.0, size=(n, 3)) * [1, 1, 0.2]
    q0[:, 2] = np.maximum(q0[:, 2], 0.5)
    cfg = sim.SimConfig(assignment="sinkhorn", localization="flooded",
                        assign_every=assign_every,
                        flood_block=64 if n >= 500 else None)
    st = sim.init_state(q0, localization=True)

    def one_chunk(state):
        state, m = sim.rollout(state, f, ControlGains(), sp, cfg, chunk)
        jnp.asarray(state.swarm.q).block_until_ready()
        return state

    st = one_chunk(st)          # compile + first-chunk warm-up

    rows = []
    for warm in ((True, False) if run_gains else (True,)):
        state = st
        c = carry
        t_gains = 0.0
        t0 = time.monotonic()
        done = 0
        while done < ticks:
            state = one_chunk(state)
            done += chunk
            if run_gains and done % redesign_every == 0:
                tg = time.monotonic()
                if warm:
                    g2, c = gainslib.solve_gains(pts, adj, max_nonedges=1,
                                                 carry=c)
                else:
                    g2 = gainslib.solve_gains(pts, adj, max_nonedges=1)
                np.asarray(g2)
                t_gains += time.monotonic() - tg
        wall = time.monotonic() - t0
        n_solves = max(1, ticks // redesign_every) if run_gains else 0
        gains_ms = (1e3 * t_gains / n_solves if run_gains
                    else warm_frac * _scale_tpu_value(
                        "admm_gain_design_n1000_s") * 1e3)
        tick_assign_ms = 1e3 * (wall - t_gains) / ticks
        if run_gains:
            measured = ticks / wall
            source = "measured"
        else:
            # host ticks+assign measured; gain term composed from the
            # committed device artifact (and labeled as such)
            measured = 1e3 / (tick_assign_ms + gains_ms / redesign_every)
            source = "scale_tpu.json"
        rows.append(_pipeline_row(
            n=n, mode="host", backend="cpu", assignment="sinkhorn",
            assign_every=assign_every, redesign_every=redesign_every,
            ticks=ticks, warm_gains=warm,
            tick_ms=tick_assign_ms - 0.0, assign_ms=0.0,
            gains_ms=gains_ms, gains_source=source,
            measured_hz=measured, quick=quick))
    return rows


def _scale_tpu_value(metric: str) -> float:
    for line in SCALE_TPU.read_text().splitlines():
        if line.strip():
            row = json.loads(line)
            if row.get("metric") == metric:
                return float(row["value"])
    raise KeyError(f"{metric} not in {SCALE_TPU}")


def composed_rows(warm_frac: float, quick: bool) -> list[dict]:
    """The headline: n=1000 stage rates from the committed
    `scale_tpu.json`, composed at the dispatch-loop cadences. The warm
    gain term scales the committed cold n=1000 solve by the MEASURED
    warm iteration fraction (`admm_warm_start`)."""
    tick_ms = 1e3 / _scale_tpu_value("flooded_tick_n1000_k16_b64_hz")
    assign_ms = 1e3 / _scale_tpu_value("sinkhorn_assign_n1000_hz")
    cold_gain_ms = 1e3 * _scale_tpu_value("admm_gain_design_n1000_s")
    rows = []
    for warm in (True, False):
        gains_ms = cold_gain_ms * (warm_frac if warm else 1.0)
        rows.append(_pipeline_row(
            n=1000, mode="composed", backend="tpu",
            assignment="sinkhorn", assign_every=ASSIGN_EVERY,
            redesign_every=REDESIGN_EVERY, ticks=0, warm_gains=warm,
            tick_ms=tick_ms, assign_ms=assign_ms, gains_ms=gains_ms,
            gains_source="scale_tpu.json", measured_hz=None,
            quick=quick))
    return rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true",
                    help="small sizes / few ticks; rows marked quick")
    ap.add_argument("--out", type=Path, default=None)
    ap.add_argument("--skip-n1000-host", action="store_true",
                    help="skip the (slow) n=1000 host row")
    args = ap.parse_args(argv)
    q = args.quick

    rows: list[dict] = []
    rows += admm_warm_rows(n=12 if q else 100, reps=1 if q else 3, quick=q)
    warm_frac = (rows[0]["warm_iters"] / max(rows[0]["cold_iters"], 1))
    rows += churn_rows(n=16 if q else 24, ticks=600 if q else 2400,
                       quick=q)
    rows += host_pipeline_rows(n=32 if q else 100,
                               ticks=120 if q else 480,
                               chunk=60 if q else 120, quick=q,
                               warm_frac=warm_frac)
    if not q and not args.skip_n1000_host:
        rows += host_pipeline_rows(n=1000, ticks=8, chunk=4, quick=q,
                                   warm_frac=warm_frac)
    rows += composed_rows(warm_frac=warm_frac, quick=q)

    for row in rows:
        print(json.dumps(row))
    if args.out is not None:
        args.out.parent.mkdir(parents=True, exist_ok=True)
        with open(args.out, "w") as fh:
            for row in rows:
                fh.write(json.dumps(row) + "\n")
        print(f"wrote {len(rows)} rows -> {args.out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
