"""SLO-detection soak for swarmwatch — the proven-detection-latency
flagship benchmark (docs/OBSERVABILITY.md §swarmwatch; ISSUE 15).

Two phases against journaled multi-worker services with swarmwatch ON:

- **chaos**: the multiworker-soak traffic shape (two rollout shape
  buckets across three tenants + single-shot work) while scripted
  `CrashPlan`s repeatedly kill individual workers mid-batch. For EVERY
  scripted kill the parent measures, **from the journal alone**, the
  kill→alert-firing detection latency: the supervisor's fleet-scope
  ``failover`` record vs the swarmwatch ``alert`` record
  (slo=worker_up, state=firing) for the same slot — both appended to
  the same events.log, so file order and wall stamps are the evidence.
  100% of kills must be detected within ``bound_s``.
- **control**: the same traffic, the same watch config, NO kills —
  **zero alerts may fire** (the false-positive half of the detection
  claim; an alarm that also fires on a healthy fleet detects nothing).

Also enforced: zero silent losses in both phases (every accepted
request terminal — the standing soak bar), sampler overhead
(`SwarmWatch.spent_s` / phase wall) under 2%, and the persisted
time-series history readable from disk after close
(`timeseries.load_store`).

Run:

    JAX_PLATFORMS=cpu python benchmarks/slo_soak.py \
        [--quick] [--out benchmarks/results/slo_detection.json]

Exit 1 on any broken promise — the artifact is only committed from a
green run. `check_results.check_slo_detection` enforces the bars AS
schema.
"""
from __future__ import annotations

import argparse
import json
import sys
import tempfile
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

RESULTS = Path(__file__).resolve().parent / "results"
WORKERS = 3
TENANTS = ("alpha", "beta", "gamma")

# the detection bound the artifact commits: sampler interval + the
# supervisor poll + scheduling slack on a 1-core host. Generous on
# purpose — the bar is "bounded and proven", not "minimal"; the
# committed capture reports the measured p50/p95/max under it.
WATCH_INTERVAL_S = 0.2
BOUND_S = 2.0


def request_mix(quick: bool) -> list[dict]:
    """Deterministic mixed stream (the multiworker-soak shape): two
    rollout shape buckets + faults + single-shot kinds across three
    tenants."""
    ticks = 60 if quick else 120
    mix = [
        {"kind": "rollout", "tenant": "alpha", "request_id": "a-roll0",
         "params": {"n": 5, "ticks": ticks, "chunk_ticks": 20,
                    "seed": 10}},
        {"kind": "rollout", "tenant": "alpha", "request_id": "a-roll1",
         "params": {"n": 5, "ticks": ticks, "chunk_ticks": 20, "seed": 11,
                    "faults": {"dropout_frac": 0.4, "drop_tick": 15,
                               "rejoin_tick": 55}}},
        {"kind": "rollout", "tenant": "beta", "request_id": "b-roll0",
         "params": {"n": 8, "ticks": ticks, "chunk_ticks": 20, "seed": 20,
                    "faults": {"link_loss": 0.2}}},
        {"kind": "rollout", "tenant": "beta", "request_id": "b-roll1",
         "params": {"n": 8, "ticks": ticks, "chunk_ticks": 20,
                    "seed": 21}},
        {"kind": "assign", "tenant": "gamma", "request_id": "g-assign",
         "params": {"n": 16, "seed": 30}},
        {"kind": "gains", "tenant": "gamma", "request_id": "g-gains",
         "params": {"n": 5, "seed": 31}},
    ]
    if not quick:
        mix.append(
            {"kind": "rollout", "tenant": "gamma",
             "request_id": "g-roll0",
             "params": {"n": 5, "ticks": ticks, "chunk_ticks": 20,
                        "seed": 32}})
    return mix


def _service_cfg(journal: str):
    from aclswarm_tpu.serve import ServiceConfig

    # rejoin backoff deliberately LONGER than the sampler interval: a
    # dead worker's gauge must stay down across >= 1 sample or the
    # detection claim would race its own rejoin (the alert still fires
    # on the committed cadence; a production rejoin is seconds anyway)
    return ServiceConfig(
        workers=WORKERS, max_batch=2, quantum_chunks=1,
        max_queue_per_tenant=6, max_queue_total=24, journal_dir=journal,
        supervise_poll_s=0.02, rejoin_base_s=0.75, rejoin_max_s=1.5,
        max_worker_restarts=8, watch=True,
        watch_interval_s=WATCH_INTERVAL_S)


def _drive(svc, mix: list[dict]) -> dict:
    """Submit the whole mix, wait everything terminal; returns
    request_id -> Result."""
    tickets = [(s, svc.submit(s["kind"], s["params"], tenant=s["tenant"],
                              request_id=s["request_id"])) for s in mix]
    return {s["request_id"]: t.result(timeout=900) for s, t in tickets}


def _events(journal: str) -> list[dict]:
    from aclswarm_tpu.telemetry.lifecycle import LifecycleLog

    rows, _ = LifecycleLog.read(Path(journal) / "events.log")
    return rows


# the worker_up gauge flips BEFORE the failover record is appended
# (declare-dead runs capacity republish + log I/O in between, tens to
# hundreds of ms on a busy 1-core host), so an alert that fired in that
# gap legitimately carries a wall stamp under the kill's — treat any
# firing within this slack as THIS kill's detection (clamped to 0 s),
# and only a strictly older unresolved firing as "already firing"
_KILL_EPS_S = 0.5


def _detections(rows: list[dict]) -> tuple[list[dict], int, int]:
    """Attribute every fleet ``failover`` record to its swarmwatch
    detection, from the journal alone. Per slot, the worker_up alert
    stream alternates firing/resolved; a kill is DETECTED either by a
    fresh firing after it (detection latency = alert - kill wall), or
    — when a repeated kill lands before the previous alert's clear
    dwell resolved it — by the alert already being in the firing state
    at kill time (the operator is already paged; no fresh transition
    exists to fire, so these count as detected with no latency sample).
    Returns (pairs, kills, firings)."""
    kills: list[tuple[str, float]] = []
    alerts: dict[str, list] = {}       # slot -> [(t, state)] in order
    for r in rows:
        if r.get("event") == "failover":
            kills.append((str(r.get("worker", "?")).split(".")[0],
                          float(r["t_wall"])))
        elif r.get("event") == "alert" and r.get("slo") == "worker_up":
            slot = str(r.get("labels", "")).strip("{}").split("=")[-1]
            alerts.setdefault(slot, []).append(
                (float(r["t_wall"]), str(r.get("state"))))
    n_firing = sum(1 for evs in alerts.values()
                   for _, s in evs if s == "firing")
    pairs = []
    consumed: set = set()
    for slot, kill_t in sorted(kills, key=lambda k: k[1]):
        evs = alerts.get(slot, [])
        state = "ok"
        for t, s in evs:
            if t <= kill_t - _KILL_EPS_S:
                state = "firing" if s == "firing" else "ok"
        if state == "firing":
            pairs.append({"slot": slot, "kill_t": kill_t,
                          "alert_t": None, "detection_s": 0.0,
                          "already_firing": True})
            continue
        fresh = next(
            (i for i, (t, s) in enumerate(evs)
             if s == "firing" and t >= kill_t - _KILL_EPS_S
             and (slot, i) not in consumed), None)
        if fresh is None:
            pairs.append({"slot": slot, "kill_t": kill_t,
                          "alert_t": None, "detection_s": None,
                          "already_firing": False})
            continue
        consumed.add((slot, fresh))
        alert_t = evs[fresh][0]
        pairs.append({"slot": slot, "kill_t": kill_t, "alert_t": alert_t,
                      "detection_s": max(0.0, alert_t - kill_t),
                      "already_firing": False})
    return pairs, len(kills), n_firing


def _silent_losses(journal: str, results: dict) -> list[str]:
    probs = []
    terminal = {"completed", "failed", "timed_out"}
    for rid, res in results.items():
        if res.status not in terminal:
            probs.append(f"{rid}: no terminal status (SILENT LOSS)")
    for reqf in Path(journal).glob("req_*.req"):
        if not reqf.with_suffix(".done").exists():
            probs.append(f"journal: {reqf.name} accepted but never "
                         "terminal")
    return probs


def run_soak(out: str | None, quick: bool) -> int:
    from aclswarm_tpu.resilience import arm_many
    from aclswarm_tpu.resilience.crash import CrashPlan
    from aclswarm_tpu.serve import SwarmService, bucket_of, place_slot
    from aclswarm_tpu.telemetry.timeseries import load_store

    t_start = time.time()
    problems: list[str] = []
    mix = request_mix(quick)
    roll_specs = [s for s in mix if s["kind"] == "rollout"]

    # ---- phase A: chaos — scripted kills, detection measured ----------
    with tempfile.TemporaryDirectory(prefix="aclswarm_slo_chaos_") as d:
        svc = SwarmService(_service_cfg(d))
        slots = list(range(WORKERS))
        slot5 = place_slot(bucket_of("rollout", roll_specs[0]["params"]),
                           slots)
        slot8 = place_slot(bucket_of("rollout", roll_specs[2]["params"]),
                           slots)
        plans = [CrashPlan(f"serve.w{slot5}", 2, "raise"),
                 CrashPlan(f"serve.w{slot5}", 5, "raise")]
        if slot8 != slot5:
            plans.append(CrashPlan(f"serve.w{slot8}", 3, "raise"))
        arm_many(plans)
        t_a = time.time()
        results = _drive(svc, mix)
        arm_many([])
        # let the last rejoin land and its worker_up alert resolve (the
        # artifact counts resolutions as evidence the machine closes)
        time.sleep(2.5)
        wall_a = time.time() - t_a
        watch_spent = svc.watch.spent_s
        watch_samples = svc.watch.sampler.samples
        persist_lost = svc.watch.sampler.lost
        svc.close()

        problems += _silent_losses(d, results)
        rows = _events(d)
        pairs, n_kills, n_firing = _detections(rows)
        resolved = sum(1 for r in rows if r.get("event") == "alert"
                       and r.get("slo") == "worker_up"
                       and r.get("state") == "resolved")
        store, ticks, torn = load_store(Path(d) / "timeseries.log")
        if ticks <= 0:
            problems.append("persisted time-series history is empty — "
                            "load_store rebuilt nothing from disk")
        if torn:
            # torn tails are legal after SIGKILL, but this run closed
            # cleanly — a torn tail here means the final tick was cut
            problems.append("timeseries.log has a torn tail after a "
                            "clean close")

    if n_kills < (1 if quick else 3):
        problems.append(f"expected >= {1 if quick else 3} scripted "
                        f"kills, journal shows {n_kills}")
    undetected = [p for p in pairs if p["detection_s"] is None]
    late = [p for p in pairs
            if p["detection_s"] is not None and p["detection_s"] > BOUND_S]
    if undetected:
        problems.append(f"{len(undetected)} kill(s) never raised a "
                        f"worker_up firing alert: {undetected}")
    if late:
        problems.append(f"{len(late)} detection(s) over the {BOUND_S} s "
                        f"bound: {late}")
    det = sorted(p["detection_s"] for p in pairs
                 if p["detection_s"] is not None
                 and not p["already_firing"])
    overhead = watch_spent / max(1e-9, wall_a)
    if overhead >= 0.02:
        problems.append(f"sampler overhead {overhead:.4f} breaches the "
                        "< 2% bar")

    # ---- phase B: control — same traffic, no kills, zero alerts ------
    with tempfile.TemporaryDirectory(prefix="aclswarm_slo_ctrl_") as d2:
        svc2 = SwarmService(_service_cfg(d2))
        t_b = time.time()
        results2 = _drive(svc2, mix)
        time.sleep(1.0)        # a late false alert must not escape the
        #                        window by microseconds
        wall_b = time.time() - t_b
        ctrl_spent = svc2.watch.spent_s
        svc2.close()
        problems += _silent_losses(d2, results2)
        rows2 = _events(d2)
        false_alerts = [r for r in rows2 if r.get("event") == "alert"
                        and r.get("state") == "firing"]
        if false_alerts:
            problems.append(
                f"{len(false_alerts)} FALSE-POSITIVE alert(s) in the "
                f"clean control soak: "
                f"{[(r.get('slo'), r.get('labels')) for r in false_alerts]}")
        ctrl_overhead = ctrl_spent / max(1e-9, wall_b)

    completed = sum(1 for r in results.values()
                    if r.status == "completed")
    row = {
        "name": "slo_detection",
        "n": 8,                        # largest rollout shape in the mix
        "backend": _backend(),
        "workers": WORKERS,
        "tenants": len(TENANTS),
        "accepted": len(results),
        "completed": completed,
        "silent_losses": len([r for r in results.values()
                              if r.status not in ("completed", "failed",
                                                  "timed_out")]),
        "kills": n_kills,
        "detected": len([p for p in pairs
                         if p["detection_s"] is not None]),
        "already_firing": len([p for p in pairs if p["already_firing"]]),
        "alerts_fired": n_firing,
        "alerts_resolved": resolved,
        "detection_s": {
            "p50": round(float(np.percentile(det, 50)), 4) if det else -1.0,
            "p95": round(float(np.percentile(det, 95)), 4) if det else -1.0,
            "max": round(max(det), 4) if det else -1.0,
        },
        "bound_s": BOUND_S,
        "watch_interval_s": WATCH_INTERVAL_S,
        "sampler_overhead_frac": round(overhead, 5),
        "sampler_samples": int(watch_samples),
        "persist_lost": int(persist_lost),
        "persisted_ticks": int(ticks),
        "series": len(store.names()),
        "control_accepted": len(results2),
        "control_completed": sum(1 for r in results2.values()
                                 if r.status == "completed"),
        "false_positives": len(false_alerts),
        "control_overhead_frac": round(ctrl_overhead, 5),
        "wall_s": round(time.time() - t_start, 1),
        "quick": bool(quick),
    }
    print(json.dumps(row, indent=1))
    for p in pairs:
        if p["already_firing"]:
            what = "alert already firing (repeated kill inside the clear dwell)"
        elif p["detection_s"] is not None:
            what = f"firing +{p['detection_s'] * 1000:.0f} ms"
        else:
            what = "NEVER DETECTED"
        print(f"  kill slot {p['slot']} @ {p['kill_t']:.3f} -> {what}")
    if problems:
        print(f"SLO SOAK FAILED ({len(problems)} broken promise(s)):")
        for p in problems:
            print(f"  {p}")
        return 1
    if out:
        p = Path(out)
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(json.dumps(row, indent=1) + "\n")
        print(f"wrote {p}")
    return 0


def _backend() -> str:
    import jax
    return jax.default_backend()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true",
                    help="smaller mix + 1 kill (CI smoke; writes no "
                         "artifact by default)")
    ap.add_argument("--out", default=None,
                    help="artifact path ('' to skip; default: the "
                         "committed path for full runs, nothing for "
                         "--quick)")
    args = ap.parse_args(argv)
    out = args.out
    if out is None:
        out = "" if args.quick else str(RESULTS / "slo_detection.json")
    return run_soak(out or None, args.quick)


if __name__ == "__main__":
    sys.exit(main())
