"""Scenario-family sweep: committed completion/recovery evidence.

The `aclswarm_tpu.scenarios` analogue of `faults_suite.py`: for every
registry family, B seeded draws run as ONE batched rollout (every trial
a DIFFERENT scenario of the family inside one compiled vmapped scan,
sanitizer on), and the on-device recovery clock (`sim.summary` — keyed
on scenario events exactly as on fault events) yields per-family

- **completion**: fraction of trials whose windowed convergence
  predicate holds in the final 20% of the horizon (the swarm absorbed
  everything the family scripted), and
- **recovery**: ticks from the LAST scenario event to reconvergence in
  the first completing trial (-1 = never recovered inside the horizon).

committed as strict rows to

    benchmarks/results/scenario_suite.json      exact-key-set schema
                                                (check_results
                                                .check_scenario_suite)

Run:
    python benchmarks/scenario_suite.py [--quick] [--out PATH]
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

RESULTS = Path(__file__).resolve().parent / "results"

N = 10          # fleet size per family row
B = 4           # seeded draws per family (one batched rollout)
TICKS = 2400    # horizon (events land by 0.75 * TICKS; window = 100)
WINDOW = 100    # 1 s supervisor convergence window at the 100 Hz tick


def run_family(family: str, *, seed: int = 1, n: int = N, b: int = B,
               ticks: int = TICKS, check_mode: str = "on") -> dict:
    import jax
    import jax.numpy as jnp

    from aclswarm_tpu import scenarios as scn, sim
    from aclswarm_tpu.analysis import invariants as invlib
    from aclswarm_tpu.core.types import (ControlGains, SafetyParams,
                                         make_formation)
    from aclswarm_tpu.sim import summary as sumlib

    fam = scn.FAMILIES[family]
    dt = jnp.result_type(float)
    r = scn.registry.formation_scale(n)
    ang = np.linspace(0, 2 * np.pi, n, endpoint=False)
    pts = np.stack([r * np.cos(ang), r * np.sin(ang),
                    np.full(n, 2.0)], 1)
    form = make_formation(jnp.asarray(pts, dt),
                          jnp.asarray(np.ones((n, n)) - np.eye(n), dt))
    sparams = SafetyParams(
        bounds_min=jnp.asarray([-100.0, -100.0, 0.0], dt),
        bounds_max=jnp.asarray([100.0, 100.0, 30.0], dt))
    flooded = fam.localization == "flooded"
    cfg = sim.SimConfig(assignment="auction", assign_every=120,
                        localization=fam.localization,
                        check_mode=check_mode)

    scens, states = [], []
    rng0 = np.random.default_rng(seed)
    for k in range(b):
        scen = scn.sample(family, seed * 1000 + k, n, dtype=dt,
                          horizon=ticks)
        scens.append(scen)
        q0 = np.asarray(pts).copy()
        q0[:, :2] += rng0.normal(size=(n, 2)) * 2.0   # short transit in
        states.append(sim.init_state(jnp.asarray(q0, dt),
                                     localization=flooded,
                                     checks=check_mode == "on",
                                     scenario=scen))
    bstate = jax.tree.map(lambda *xs: jnp.stack(xs), *states)
    bform = jax.tree.map(lambda *xs: jnp.stack(xs), *([form] * b))
    carry = sumlib.init_carry(n, WINDOW, dtype=dt, batch=b)

    chunk = 600
    conv = np.zeros((b, 0), bool)
    rec = np.zeros((b, 0), np.int32)
    ev = np.zeros((b, 0), bool)
    for c0 in range(0, ticks, chunk):
        bstate, carry, summ = sumlib.batched_rollout_summary(
            bstate, carry, bform, ControlGains(), sparams, cfg, chunk,
            None, 0, window=WINDOW, takeoff_alt=2.0)
        if check_mode == "on":
            codes = np.asarray(summ.inv_code)
            for bb in range(b):
                invlib.raise_on_violation(codes[bb], trial=bb, tick0=c0)
        conv = np.concatenate([conv, np.asarray(summ.conv_all)], axis=1)
        rec = np.concatenate([rec, np.asarray(summ.recovery_ticks)],
                             axis=1)
        ev = np.concatenate([ev, np.asarray(summ.scen_event)], axis=1)

    tail = int(0.8 * ticks)
    completed = [bool(conv[bb, tail:].any()) for bb in range(b)]
    # recovery: first clock fire after the LAST scripted event, taken
    # from the first COMPLETING trial (a transient reconvergence in a
    # trial that later diverged is not recovery evidence)
    recovery = -1
    for bb in range(b):
        if not completed[bb]:
            continue
        evs = np.nonzero(ev[bb])[0]
        if evs.size == 0:
            continue
        fired = np.nonzero(rec[bb, evs[-1]:] >= 0)[0]
        if fired.size:
            recovery = int(rec[bb, evs[-1] + fired[0]])
            break
    return dict(completion=sum(completed) / b, recovery=recovery,
                events=int(ev.sum()), trials=b)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true",
                    help="short horizon smoke (rows marked quick)")
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--families", action="append", default=None)
    ap.add_argument("--out", default=str(RESULTS / "scenario_suite.json"))
    ap.add_argument("--check-mode", choices=("off", "on"), default="on")
    args = ap.parse_args(argv)

    import jax

    from aclswarm_tpu import scenarios as scn

    ticks = 600 if args.quick else TICKS
    fams = args.families or sorted(scn.FAMILIES)
    rows, failed = [], []
    for family in fams:
        print(f"=== scenario family {family} (B={B}) ===", flush=True)
        t0 = time.time()
        try:
            out = run_family(family, seed=args.seed, ticks=ticks,
                             check_mode=args.check_mode)
        except Exception as e:   # noqa: BLE001 — recorded, not hidden
            failed.append(f"{family}: {e}")
            print(f"FAILED {family}: {e} — continuing", flush=True)
            continue
        wall = round(time.time() - t0, 1)
        base = dict(n=N, family=family, trials=out["trials"],
                    seed=args.seed, ticks=ticks, events=out["events"],
                    wall_s=wall, device=jax.default_backend(),
                    quick=bool(args.quick))
        rows.append(dict(base, name=f"scenario_{family}_completion",
                         kind="completion", unit="frac",
                         value=out["completion"]))
        rows.append(dict(base, name=f"scenario_{family}_recovery",
                         kind="recovery", unit="ticks",
                         value=out["recovery"],
                         recovered=out["recovery"] >= 0))
        for rrow in rows[-2:]:
            print(json.dumps(rrow), flush=True)

    RESULTS.mkdir(exist_ok=True)
    out_path = Path(args.out)
    with out_path.open("w") as f:
        for rrow in rows:
            f.write(json.dumps(rrow) + "\n")
    print(f"wrote {out_path} ({len(rows)} rows)")

    from check_results import check_file
    probs = check_file(out_path)
    if probs:
        print("SCHEMA DRIFT in freshly written artifact:")
        for p in probs:
            print(f"  {p}")
        return 1
    if failed:
        print(f"{len(failed)} family(ies) FAILED:")
        for c in failed:
            print(f"  {c}")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
