"""Monte-Carlo trial evidence for every runnable north-star config.

The reference's unit of result is a *trial*: `trials.sh -m K` followed by
the `analyze_simtrials.m:38-59` reduction into completion %, convergence
times, avoidance time, and assignment counts. This driver produces that
table for the framework's north-star configs (BASELINE.md) and commits it
as artifacts:

    benchmarks/results/trials_<config>.csv     one reference-schema row
                                               per completed trial
    benchmarks/results/trials_summary.json     the analyze() reduction per
                                               config + environment info

Run (on the bench TPU; CPU works but slower):

    python benchmarks/trials_suite.py [--quick] [--only CONFIG] [--serve]

All configs run `dynamics=doubleint` (the honest second-order model,
golden-pinned in tests/test_dynamics_golden.py).

``--serve`` routes every grid cell through the swarmserve layer
(docs/SERVICE.md) as a service CLIENT: each cell is one journaled-style
request with the unified retry/degrade executor underneath, a failing
cell terminates with a structured error instead of an exception, and
the committed summary carries the service's execution provenance
(retries / degraded markers / request counts) — serving as the flagship
benchmark axis, per ROADMAP open item 2.
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from aclswarm_tpu.harness import trials as triallib

RESULTS = Path(__file__).resolve().parent / "results"

# shared bases for the faithful CBAA+flooded rows and their tuned
# variants ("tuned = faithful + knob" must stay structurally true — a
# base-config change propagates to every derived row)
SIMFORM100_CBAA_BASE = dict(
    formation="simform100", assignment="cbaa",
    localization="flooded", colavoid_neighbors=16, chunk_ticks=100,
    sim_l=40.0, sim_w=40.0, sim_h=3.0, sim_min_dist=3.0,
    init_area_w=40.0, init_area_h=40.0, init_radius=1.0,
    room_x=100.0, room_y=100.0, room_z=30.0)

SIMFORM1000_CBAA_BASE = dict(
    formation="simform1000", assignment="cbaa",
    localization="flooded", flood_block=64, flood_phases=2,
    cbaa_task_block=64,
    colavoid_neighbors=16, chunk_ticks=100,
    sim_l=130.0, sim_w=130.0, sim_h=3.0, sim_min_dist=3.0,
    init_area_w=120.0, init_area_h=120.0, init_radius=1.0,
    room_x=200.0, room_y=200.0, room_z=30.0,
    max_vel_xy=1.0, max_vel_z=0.5,
    max_accel_xy=1.0, max_accel_z=1.0, trial_timeout=1200.0,
    e_xy_thr=1.0, e_z_thr=0.3, kd=0.0005, K1_xy=0.005,
    gain_scale=0.15)

# (name, TrialConfig overrides, trials, quick-trials)
CONFIGS = [
    # flagship demo group (BASELINE.md config 1)
    ("swarm6_3d", dict(formation="swarm6_3d"), 20, 2),
    # random noncomplete graphs, solve-gains-on-dispatch (config 2 shape)
    ("simform10", dict(formation="simform10"), 20, 2),
    ("simform20", dict(formation="simform20"), 10, 1),
    # decentralized CBAA + flooded localization (the real information
    # model) on the shipped sparse group
    ("swarm6_sparse_cbaa_flooded",
     dict(formation="swarm6_sparse", assignment="cbaa",
          localization="flooded"), 10, 1),
    # mid-size shipped group on the grid-with-diagonals sparse graph
    ("grid9", dict(formation="grid9"), 10, 1),
    # parity with the reference's largest committed group (mitacl15):
    # 15 agents, 3 formations, sparse 33-edge graph, precalc'd gains
    ("swarm15", dict(formation="swarm15"), 10, 1),
    # scale group: 100 agents, gains solved on dispatch (config 3)
    ("swarm100", dict(formation="swarm100", assignment="sinkhorn",
                      colavoid_neighbors=16), 5, 1),
    # the fully-faithful information model at 100 agents: decentralized
    # CBAA consensus auctions (fixed-point early exit, bit-identical) fed
    # by flooded-localization estimate tables — reference-default control
    # parameters throughout; only the generation boxes and the 3 m
    # avoidance-shell spacing (docs/SCALE_TUNING.md §5) are scaled
    ("simform100_cbaa_flooded", dict(SIMFORM100_CBAA_BASE), 10, 1),
    # north-star scale (config 4/5 shape, closed loop): 1000 agents,
    # random rigid graphs, Sinkhorn auctions, on-dispatch ADMM gain
    # design, k=16 avoidance pruning. Nothing in the reference ever flew
    # more than 15 vehicles (`formations.yaml:251`); every deviation from
    # the reference's SIL defaults below is a launch-file-parameter-class
    # knob with its measured failure mode commented inline — supervisor
    # *predicates* are untouched. The 0.5 m/s reference saturation alone
    # is >500 s of transit at this scale, and the reference deadbands
    # 0.3/0.1 m leave a permanent >1 m/s atan-term noise floor on ~9% of
    # vehicles at degree ~998 (see TrialConfig.e_xy_thr).
    ("simform1000",
     dict(formation="simform1000", assignment="sinkhorn",
          colavoid_neighbors=16, chunk_ticks=100,
          # formation spacing >= 2 * d_avoid_thresh (3 m): parked vehicles
          # sit OUTSIDE each other's VO detection shells. At the
          # reference's default 2 m spacing every settled vehicle
          # permanently triggers its neighbors' avoidance, and 1000-agent
          # crossing flows jam into a drift attractor (seed 3, measured —
          # convergence then rides luck; 2.0 m is fine at the reference's
          # n<=15 densities). Boxes scale to keep the packing feasible.
          sim_l=130.0, sim_w=130.0, sim_h=3.0, sim_min_dist=3.0,
          init_area_w=120.0, init_area_h=120.0, init_radius=1.0,
          room_x=200.0, room_y=200.0, room_z=30.0,
          # 1 m/s with matching 1 m/s^2 authority: stopping distance
          # 0.5 m inside the 1.5 m avoidance shell (2 m/s needs 4 m and
          # overruns it — measured gridlock)
          max_vel_xy=1.0, max_vel_z=0.5,
          max_accel_xy=1.0, max_accel_z=1.0, trial_timeout=1200.0,
          e_xy_thr=1.0, e_z_thr=0.3,
          # deg*kd at reference strength: 0.5/deg, deg ~= n-1
          kd=0.0005,
          # K1*|q_ij| at reference strength: the scale force multiplies
          # pair distance (20x the reference's 5 m formations here)
          K1_xy=0.005,
          # row stiffness back to reference range (~4.9 -> ~0.7; see
          # TrialConfig.gain_scale)
          gain_scale=0.15,
          # break Sinkhorn near-tie churn (SimConfig.assign_eps)
          assign_eps=0.01,
          # dissolve keep-out pair-traps: at 1000-vehicle crossing-flow
          # densities a pair occasionally penetrates the 1.2 m keep-out
          # (measured: seed 1 under the round-4 engine locks two vehicles
          # at 1.19 m, z-separated, and gridlocks — docs/SCALE_TUNING.md
          # par.6); the radial escape re-separates them and the trial
          # completes. Reference semantics (knob off) is the
          # simform100_cbaa_flooded row's operating point.
          keepout_repulse_vel=0.3), 5, 1),
    # the north-star scale WITH the faithful information model: control
    # consumes flooded-localization estimate tables (the reference's
    # actual L3, `localization_ros.cpp`) instead of ground truth.
    # flood_block bounds merge memory; flood_phases=2 spreads the O(n^3)
    # merge across the 50 Hz window so no tick spikes below 100 Hz
    # (`localization.tick_phased`). All other knobs = simform1000's
    # EXCEPT keepout_repulse_vel, deliberately off here: seeds 1-5
    # completed 5/5 without it (committed CSV), so this row keeps one
    # fewer divergence from reference avoidance semantics; enable it if a
    # future seed hits the keep-out pair-trap of SCALE_TUNING par.6.
    ("simform1000_flooded",
     dict(formation="simform1000", assignment="sinkhorn",
          localization="flooded", flood_block=64, flood_phases=2,
          colavoid_neighbors=16, chunk_ticks=100,
          sim_l=130.0, sim_w=130.0, sim_h=3.0, sim_min_dist=3.0,
          init_area_w=120.0, init_area_h=120.0, init_radius=1.0,
          room_x=200.0, room_y=200.0, room_z=30.0,
          max_vel_xy=1.0, max_vel_z=0.5,
          max_accel_xy=1.0, max_accel_z=1.0, trial_timeout=1200.0,
          e_xy_thr=1.0, e_z_thr=0.3, kd=0.0005, K1_xy=0.005,
          gain_scale=0.15, assign_eps=0.01), 5, 1),
    # the FULLY-faithful mode at the north-star scale: the reference's
    # actual decentralized pipeline — per-agent local alignment -> CBAA
    # max-consensus auctions over adj∘assignment (`auctioneer.cpp:50-51,
    # 469-542`) fed by flooded-localization estimate tables
    # (`localization_ros.cpp:152-185`) — closed loop at 1000 agents.
    # cbaa_task_block bounds the consensus broadcast at O(n^2 B)
    # (bit-identical; 4 GB dense would not fit alongside the flood).
    # assign_eps is inapplicable: CBAA carries the reference's own
    # accept-any-different + detect-and-skip semantics internally
    # (`shouldUseAssignment`/`isValidAssignment`), so the Sinkhorn
    # churn-breaking margin is not needed and not wired to this path
    # (measured: the post-dispatch CBAA churn settles by itself at
    # ~60 s and every auction stays valid). All physical/control knobs =
    # simform1000's (each one a launch-file-parameter-class scale knob
    # with its measured failure mode documented there; supervisor
    # predicates untouched) — INCLUDING keepout_repulse_vel: seed 1
    # reproduces the SCALE_TUNING par.6 keep-out pair-trap under CBAA
    # (first formation converges at 92 s but one trapped pair holds
    # CA-active >= 95% from takeoff; GRIDLOCK persists 90 s ->
    # TERMINATE at 103 s, diagnosed chunk-by-chunk).
    ("simform1000_cbaa_flooded",
     dict(SIMFORM1000_CBAA_BASE, keepout_repulse_vel=0.3), 5, 1),
    # the TUNED operating points: the faithful rows with the opt-in
    # avoidance escapes on (`keepout_repulse_vel` for inside-keep-out
    # pair traps, `colavoid_dz_ignore` for the z-aware sector cylinder —
    # docs/SCALE_TUNING.md §6/§7 demonstrate each against the measured
    # gridlock it dissolves). These rows exist so the escape claims are
    # Monte-Carlo evidence, not one-off re-flies; the reference-faithful
    # rows above remain the official results.
    #
    # MEASURED KNOB INTERACTION (committed as evidence, round 5): at
    # simform100's crossing density BOTH knobs together score 70 %
    # (`trials_simform100_cbaa_flooded_escapes.csv`) — WORSE than the
    # 90 % knob-off row; seed 4 completes with dz alone but fails with
    # both. The escapes are targeted fixes for specific measured traps,
    # not universal improvements: they reshuffle the trajectory
    # ensemble, and the repulse knob's 0.3 m/s injections are net
    # harmful at 3 m spacing. Hence the committed tuned row for
    # simform100 is dz-ONLY (the §6-addendum configuration).
    ("simform100_cbaa_flooded_escapes",
     dict(SIMFORM100_CBAA_BASE, keepout_repulse_vel=0.3,
          colavoid_dz_ignore=1.5), 10, 1),
    ("simform100_cbaa_flooded_dz",
     dict(SIMFORM100_CBAA_BASE, colavoid_dz_ignore=1.5), 10, 1),
    ("simform1000_cbaa_flooded_escapes",
     dict(SIMFORM1000_CBAA_BASE, keepout_repulse_vel=0.3,
          colavoid_dz_ignore=1.5), 5, 1),
]


# dispositioned sub-100 rows (the exit gate flags only UNEXPECTED drops):
# the faithful rows' deterministic failing seeds are analyzed
# tick-by-tick in docs/SCALE_TUNING.md §6/§7 and deliberately left at
# reference avoidance semantics, and the both-knobs simform100 row is
# committed as negative evidence of the knob interaction.
EXPECTED_PCT = {
    "simform100_cbaa_flooded": 90.0,
    "simform1000_cbaa_flooded": 80.0,
    "simform100_cbaa_flooded_escapes": 70.0,
}


def run_config(name: str, overrides: dict, m: int, seed: int = 1,
               batch: int = 1, checkpoint_dir: str | None = None,
               resume: bool = False) -> dict:
    # trials append to a TEMP file which atomically replaces the
    # committed CSV only after the config finishes — a crashed or wedged
    # run (observed: the device tunnel can hang before trial 0 ends)
    # must never destroy committed evidence
    out = RESULTS / f"trials_{name}.csv"
    tmp = RESULTS / f".trials_{name}.csv.tmp"
    if not (checkpoint_dir and resume):
        # resuming keeps the crashed run's partial tmp: its rows are the
        # finished trials the done-markers will replay (idempotent
        # appends dedupe by trial id — harness.trials.run_trials)
        tmp.unlink(missing_ok=True)
    overrides = dict(overrides)
    if checkpoint_dir:
        overrides["checkpoint_dir"] = str(Path(checkpoint_dir) / name)
        overrides["resume"] = resume
    if batch > 1:
        # the batched rollout shares the auction phase across trials, so
        # the FSM action latency (chunk) must align to the auction period
        # (docs/BATCHED_TRIALS.md); bump the chunk up to the next multiple
        ae = overrides.get("assign_every",
                           triallib.TrialConfig.assign_every)
        ct = overrides.get("chunk_ticks", triallib.TrialConfig.chunk_ticks)
        overrides["chunk_ticks"] = ct if ct % ae == 0 else -(-ct // ae) * ae
        overrides["batch"] = min(batch, m)
    cfg = triallib.TrialConfig(trials=m, seed=seed, out=str(tmp),
                               verbose=True, **overrides)
    t0 = time.time()
    stats = triallib.run_trials(cfg)
    if tmp.exists():
        tmp.replace(out)
    else:
        # zero completed trials (e.g. every trial timed out in a
        # degraded environment): keep whatever committed evidence
        # exists — the summary row records the 0 % honestly, and
        # deleting the prior CSV here would be exactly the evidence
        # loss this path exists to prevent
        stats["csv_kept_from_prior_run"] = out.exists()
    stats["wall_s"] = round(time.time() - t0, 1)
    # batch size + per-trial wall clock: the batched-rollout win (or the
    # serial baseline) stays visible in the committed summary
    stats["batch"] = getattr(cfg, "batch", 1)
    stats["wall_s_per_trial"] = round(stats["wall_s"] / max(m, 1), 2)
    stats["config"] = {k: v for k, v in dataclasses.asdict(cfg).items()
                       if k not in ("out", "verbose")}
    # the recorded config must name the committed artifact, not the temp
    stats["config"]["csv"] = out.name
    return stats


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true",
                    help="1-2 trials per config (smoke)")
    ap.add_argument("--only", default=None, help="run a single config")
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--batch", type=int, default=1,
                    help="trials per device launch (> 1 uses the vmapped "
                         "batched rollout; chunk_ticks auto-aligns to "
                         "assign_every)")
    ap.add_argument("--checkpoint-dir", default=None,
                    help="per-config chunk-boundary checkpoints + "
                    "done-markers (docs/RESILIENCE.md): a killed suite "
                    "resumes mid-grid AND mid-rollout")
    ap.add_argument("--resume", action="store_true",
                    help="skip configs already recorded in "
                    "trials_summary.json and resume the interrupted one "
                    "from its checkpoints (needs --checkpoint-dir for "
                    "mid-rollout resume)")
    ap.add_argument("--serve", action="store_true",
                    help="run every grid cell as a swarmserve client "
                    "request (docs/SERVICE.md): structured per-cell "
                    "errors + execution provenance in the summary")
    args = ap.parse_args(argv)

    import jax
    from aclswarm_tpu.resilience import InjectedCrash
    from aclswarm_tpu.utils.retry import ExecutionFailure
    RESULTS.mkdir(exist_ok=True)

    svc = None
    if args.serve:
        from aclswarm_tpu.serve import (ServiceConfig, SwarmService,
                                        submit_and_wait)
        svc = SwarmService(ServiceConfig(max_queue_per_tenant=64,
                                         max_queue_total=64))
        svc.register(
            "trials_config",
            lambda p: run_config(p["name"], p["overrides"], p["m"],
                                 p["seed"], batch=p["batch"],
                                 checkpoint_dir=p["checkpoint_dir"],
                                 resume=p["resume"]))

    def _cell_stats(name, overrides, n_trials):
        """One grid cell: direct call, or a serve-client request whose
        structured failure is re-raised into the existing recorded-
        cell-failure path."""
        if svc is None:
            return run_config(name, overrides, n_trials, args.seed,
                              batch=args.batch,
                              checkpoint_dir=args.checkpoint_dir,
                              resume=args.resume)
        # submit_and_wait owns the liveness-aware wait: a DEAD worker
        # (scripted crash drill, unexpected bug) comes back as a
        # structured `worker_died` result instead of hanging the suite
        res = submit_and_wait(
            svc, "trials_config",
            {"name": name, "overrides": overrides, "m": n_trials,
             "seed": args.seed, "batch": args.batch,
             "checkpoint_dir": args.checkpoint_dir,
             "resume": args.resume},
            tenant="suite", request_id=f"cell-{name}")
        if not res.ok:
            raise RuntimeError(f"serve cell {res.status}: "
                               f"{res.error.code}: {res.error.message}")
        return res.value

    summary = {
        "backend": jax.default_backend(),
        "device": str(jax.devices()[0]),
        "configs": {},
    }
    path = RESULTS / "trials_summary.json"
    prior = json.loads(path.read_text()).get("configs", {}) \
        if path.exists() else {}

    def _flush_summary():
        # incremental + idempotent: a mid-grid crash keeps every
        # completed cell's stats (merged over the committed file)
        merged = dict(prior)
        merged.update(summary["configs"])
        path.write_text(json.dumps(dict(summary, configs=merged),
                                   indent=1))

    def _cell_marker(name):
        return (Path(args.checkpoint_dir) / f"{name}.cell.done"
                if args.checkpoint_dir else None)

    failed = []
    for name, overrides, m, mq in CONFIGS:
        if args.only and name != args.only:
            continue
        n_trials = mq if args.quick else m
        marker = _cell_marker(name)
        if args.resume and marker is not None and marker.exists():
            # mid-grid resume: THIS sweep already finished the cell (the
            # marker lives in the sweep's checkpoint dir — the committed
            # summary alone is not progress evidence, it carries prior
            # runs); its stats are in trials_summary.json already
            print(f"=== {name}: cell marker present, skipping "
                  "(--resume) ===", flush=True)
            continue
        print(f"=== {name} (m={n_trials}) ===", flush=True)
        t0 = time.time()
        try:
            stats = _cell_stats(name, overrides, n_trials)
        except InjectedCrash:
            raise          # scripted preemption: die as scripted
        except Exception as e:      # noqa: BLE001 — recorded, not hidden
            # one failing cell must not lose the rest of the grid: the
            # failure is recorded as evidence and the sweep continues,
            # failing at the end with the summary
            failed.append(f"{name}: {e}")
            fail = ExecutionFailure(stage=name,
                                    error=f"{type(e).__name__}: {e}",
                                    elapsed_s=time.time() - t0)
            summary["configs"][name] = {
                "error": fail.error, "wall_s": round(fail.elapsed_s, 1),
                "execution_failures": [fail.to_row()]}
            _flush_summary()
            print(f"FAILED {name}: {e} — continuing the grid", flush=True)
            continue
        summary["configs"][name] = stats
        _flush_summary()
        if marker is not None:
            marker.parent.mkdir(parents=True, exist_ok=True)
            marker.touch()
        print(json.dumps({k: v for k, v in stats.items()
                          if k != "config"}), flush=True)

    summary["configs"] = {**prior, **summary["configs"]}
    if svc is not None:
        svc.close()
        # serving provenance: request counts + any retry/degraded
        # markers the executor recorded while running the grid
        summary["serve"] = svc.row_fields()
    path.write_text(json.dumps(summary, indent=1))
    print(f"wrote {path}")
    bad = [k for k, v in summary["configs"].items()
           if "error" not in v
           and v["completion_pct"] < EXPECTED_PCT.get(k, 100.0)]
    if bad:
        print(f"below expected completion: {bad}")
    if failed:
        print(f"{len(failed)} grid cell(s) FAILED (recorded in "
              "trials_summary.json):")
        for c in failed:
            print(f"  {c}")
    return 1 if (bad or failed) else 0


if __name__ == "__main__":
    sys.exit(main())
