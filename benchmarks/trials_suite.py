"""Monte-Carlo trial evidence for every runnable north-star config.

The reference's unit of result is a *trial*: `trials.sh -m K` followed by
the `analyze_simtrials.m:38-59` reduction into completion %, convergence
times, avoidance time, and assignment counts. This driver produces that
table for the framework's north-star configs (BASELINE.md) and commits it
as artifacts:

    benchmarks/results/trials_<config>.csv     one reference-schema row
                                               per completed trial
    benchmarks/results/trials_summary.json     the analyze() reduction per
                                               config + environment info

Run (on the bench TPU; CPU works but slower):

    python benchmarks/trials_suite.py [--quick] [--only CONFIG]

All configs run `dynamics=doubleint` (the honest second-order model,
golden-pinned in tests/test_dynamics_golden.py).
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from aclswarm_tpu.harness import trials as triallib

RESULTS = Path(__file__).resolve().parent / "results"

# (name, TrialConfig overrides, trials, quick-trials)
CONFIGS = [
    # flagship demo group (BASELINE.md config 1)
    ("swarm6_3d", dict(formation="swarm6_3d"), 20, 2),
    # random noncomplete graphs, solve-gains-on-dispatch (config 2 shape)
    ("simform10", dict(formation="simform10"), 20, 2),
    ("simform20", dict(formation="simform20"), 10, 1),
    # decentralized CBAA + flooded localization (the real information
    # model) on the shipped sparse group
    ("swarm6_sparse_cbaa_flooded",
     dict(formation="swarm6_sparse", assignment="cbaa",
          localization="flooded"), 10, 1),
    # scale group: 100 agents, gains solved on dispatch (config 3)
    ("swarm100", dict(formation="swarm100", assignment="sinkhorn",
                      colavoid_neighbors=16), 5, 1),
    # north-star scale (config 4/5 shape, closed loop): 1000 agents,
    # random rigid graphs, Sinkhorn auctions, on-dispatch ADMM gain
    # design, k=16 avoidance pruning. Boxes scale with n (the reference's
    # 15 x 15 trial box fits ~60 cylinders at 2 m spacing; random
    # sequential packing of 1000 needs ~5,700 m^2): generation 110 x 110,
    # ground starts 100 x 100, room 200 x 200. Nothing in the reference
    # ever flew more than 15 vehicles (`formations.yaml:251`).
    ("simform1000",
     dict(formation="simform1000", assignment="sinkhorn",
          colavoid_neighbors=16, chunk_ticks=100,
          sim_l=110.0, sim_w=110.0, sim_h=3.0,
          init_area_w=100.0, init_area_h=100.0,
          room_x=200.0, room_y=200.0, room_z=30.0), 3, 1),
]


def run_config(name: str, overrides: dict, m: int, seed: int = 1) -> dict:
    out = RESULTS / f"trials_{name}.csv"
    out.unlink(missing_ok=True)
    cfg = triallib.TrialConfig(trials=m, seed=seed, out=str(out),
                               verbose=True, **overrides)
    t0 = time.time()
    stats = triallib.run_trials(cfg)
    stats["wall_s"] = round(time.time() - t0, 1)
    stats["config"] = {k: v for k, v in dataclasses.asdict(cfg).items()
                       if k not in ("out", "verbose")}
    return stats


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true",
                    help="1-2 trials per config (smoke)")
    ap.add_argument("--only", default=None, help="run a single config")
    ap.add_argument("--seed", type=int, default=1)
    args = ap.parse_args(argv)

    import jax
    RESULTS.mkdir(exist_ok=True)
    summary = {
        "backend": jax.default_backend(),
        "device": str(jax.devices()[0]),
        "configs": {},
    }
    for name, overrides, m, mq in CONFIGS:
        if args.only and name != args.only:
            continue
        n_trials = mq if args.quick else m
        print(f"=== {name} (m={n_trials}) ===", flush=True)
        stats = run_config(name, overrides, n_trials, args.seed)
        summary["configs"][name] = stats
        print(json.dumps({k: v for k, v in stats.items()
                          if k != "config"}), flush=True)

    path = RESULTS / "trials_summary.json"
    existing = {}
    if path.exists():
        existing = json.loads(path.read_text())
        existing.get("configs", {}).update(summary["configs"])
        summary["configs"] = existing.get("configs", summary["configs"])
    path.write_text(json.dumps(summary, indent=1))
    print(f"wrote {path}")
    bad = [k for k, v in summary["configs"].items()
           if v["completion_pct"] < 100.0]
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
