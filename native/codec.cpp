// Native wire-API codec: the aclswarm_msgs boundary as bytes, C ABI.
//
// Implements the exact frame + payload layouts documented in
// aclswarm_tpu/interop/codec.py (the Python reference implementation);
// the two are byte-identical by test (tests/test_interop.py). This is the
// piece a non-Python host process (the reference's C++ vehicle nodes, a
// ROS bridge, a telemetry recorder) links against to speak planner
// traffic with zero dependencies — the reference's equivalent machinery
// is the ROS message (de)serialization generated from
// aclswarm_msgs/msg/*.msg and carried by TCPROS.
//
// Build: make -C native   (produces build/libaclswarm_native.so)
//
// Conventions: all integers little-endian (asserted at build time), no
// struct padding — buffers are assembled byte-by-byte via memcpy so the
// code is UB-free on any alignment. Every encode_* returns the number of
// bytes written, or -1 if the output buffer is too small. Every decode_*
// returns 0 on success, negative error codes otherwise.

#include <cstdint>
#include <cstring>

static_assert(sizeof(float) == 4 && sizeof(double) == 8, "IEEE 754 required");

namespace {

constexpr uint32_t kMagic = 0x4D575341u;  // "ASWM" little-endian
constexpr uint8_t kVersion = 1;
constexpr size_t kFrameHeader = 16;  // magic,u8 ver,u8 type,u16 rsvd,u32 len,u32 crc

// little-endian only: the framework targets x86-64/aarch64 hosts
#if defined(__BYTE_ORDER__) && __BYTE_ORDER__ != __ORDER_LITTLE_ENDIAN__
#error "big-endian hosts unsupported"
#endif

// ---- CRC32 (IEEE 802.3 / zlib polynomial, reflected) ----
uint32_t crc_table[256];
bool crc_init_done = []() {
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = i;
    for (int k = 0; k < 8; ++k) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc_table[i] = c;
  }
  return true;
}();

uint32_t crc32_ieee(const uint8_t* p, size_t n) {
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; ++i) c = crc_table[(c ^ p[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

// ---- byte-stream writer/reader ----
struct Writer {
  uint8_t* out;
  size_t cap, off = 0;
  bool ok = true;
  void bytes(const void* p, size_t n) {
    if (!ok || off + n > cap) { ok = false; return; }
    std::memcpy(out + off, p, n);
    off += n;
  }
  template <typename T> void scalar(T v) { bytes(&v, sizeof(T)); }
  void str(const char* s) {
    size_t n = s ? std::strlen(s) : 0;
    if (n > 0xFFFF) { ok = false; return; }
    scalar<uint16_t>((uint16_t)n);
    bytes(s, n);
  }
};

struct Reader {
  const uint8_t* in;
  size_t len, off = 0;
  bool ok = true;
  void bytes(void* p, size_t n) {
    if (!ok || off + n > len) { ok = false; return; }
    std::memcpy(p, in + off, n);
    off += n;
  }
  template <typename T> T scalar() {
    T v{};
    bytes(&v, sizeof(T));
    return v;
  }
  // copies the string into dst (cap incl. NUL); always NUL-terminates
  void str(char* dst, size_t cap) {
    uint16_t n = scalar<uint16_t>();
    if (!ok || off + n > len) { ok = false; return; }
    if (dst && cap) {
      size_t c = n < cap - 1 ? n : cap - 1;
      std::memcpy(dst, in + off, c);
      dst[c] = 0;
    }
    off += n;
  }
};

void put_header(Writer& w, uint32_t seq, double stamp, const char* frame_id) {
  w.scalar<uint32_t>(seq);
  w.scalar<double>(stamp);
  w.str(frame_id);
}

void get_header(Reader& r, uint32_t* seq, double* stamp, char* frame,
                size_t frame_cap) {
  uint32_t s = r.scalar<uint32_t>();
  double st = r.scalar<double>();
  if (seq) *seq = s;
  if (stamp) *stamp = st;
  r.str(frame, frame_cap);
}

int64_t finish_frame(Writer& w, uint8_t type) {
  if (!w.ok) return -1;
  size_t plen = w.off - kFrameHeader;
  uint8_t* f = w.out;
  uint32_t magic = kMagic, len32 = (uint32_t)plen;
  uint32_t crc = crc32_ieee(f + kFrameHeader, plen);
  std::memcpy(f, &magic, 4);
  f[4] = kVersion;
  f[5] = type;
  f[6] = f[7] = 0;
  std::memcpy(f + 8, &len32, 4);
  std::memcpy(f + 12, &crc, 4);
  return (int64_t)w.off;
}

Writer begin_frame(uint8_t* out, size_t cap) {
  Writer w{out, cap};
  w.off = kFrameHeader;  // header patched by finish_frame
  if (cap < kFrameHeader) w.ok = false;
  return w;
}

}  // namespace

extern "C" {

// message type tags (aclswarm_tpu/interop/messages.py MSG_*)
enum {
  ASW_FORMATION = 1,
  ASW_CBAA = 2,
  ASW_ESTIMATES = 3,
  ASW_STATUS = 4,
  ASW_DIST_CMD = 5,
  ASW_ASSIGNMENT = 6,
  ASW_FLIGHT_MODE = 7,
  ASW_SAFETY_ARRAY = 8,
};

uint32_t asw_crc32(const uint8_t* p, uint64_t n) { return crc32_ieee(p, n); }

// Validate a frame; returns the message type (>0) and sets *payload_off /
// *payload_len, or a negative error: -1 short, -2 magic, -3 version,
// -4 truncated, -5 crc.
int asw_parse_frame(const uint8_t* buf, uint64_t len, uint64_t* payload_off,
                    uint64_t* payload_len) {
  if (len < kFrameHeader) return -1;
  uint32_t magic, plen, crc;
  std::memcpy(&magic, buf, 4);
  std::memcpy(&plen, buf + 8, 4);
  std::memcpy(&crc, buf + 12, 4);
  if (magic != kMagic) return -2;
  if (buf[4] != kVersion) return -3;
  if (len < kFrameHeader + (uint64_t)plen) return -4;
  if (crc32_ieee(buf + kFrameHeader, plen) != crc) return -5;
  if (payload_off) *payload_off = kFrameHeader;
  if (payload_len) *payload_len = plen;
  return buf[5];
}

// ---- Formation ----
int64_t asw_encode_formation(uint32_t seq, double stamp, const char* frame_id,
                             const char* name, uint32_t n,
                             const double* points /* n*3 */,
                             const uint8_t* adjmat /* n*n */,
                             const float* gains /* 9*n*n or NULL */,
                             uint8_t* out, uint64_t cap) {
  Writer w = begin_frame(out, cap);
  put_header(w, seq, stamp, frame_id);
  w.str(name);
  w.scalar<uint32_t>(n);
  w.bytes(points, (size_t)n * 3 * 8);
  w.bytes(adjmat, (size_t)n * n);
  w.scalar<uint8_t>(gains ? 1 : 0);
  if (gains) w.bytes(gains, (size_t)9 * n * n * 4);
  return finish_frame(w, ASW_FORMATION);
}

// Phase 1: query n (and gains presence) so the caller can size buffers.
int asw_formation_dims(const uint8_t* buf, uint64_t len, uint32_t* n,
                       int* has_gains) {
  uint64_t off, plen;
  if (asw_parse_frame(buf, len, &off, &plen) != ASW_FORMATION) return -1;
  Reader r{buf + off, plen};
  get_header(r, nullptr, nullptr, nullptr, 0);
  r.str(nullptr, 0);
  uint32_t nn = r.scalar<uint32_t>();
  if (!r.ok) return -2;
  if (r.off + (uint64_t)nn * 3 * 8 + (uint64_t)nn * nn + 1 > plen) return -3;
  if (n) *n = nn;
  if (has_gains) *has_gains = buf[off + r.off + nn * 3 * 8 + nn * nn] != 0;
  return 0;
}

int asw_decode_formation(const uint8_t* buf, uint64_t len, uint32_t* seq,
                         double* stamp, char* frame_id, uint64_t frame_cap,
                         char* name, uint64_t name_cap, double* points,
                         uint8_t* adjmat, float* gains /* may be NULL */) {
  uint64_t off, plen;
  if (asw_parse_frame(buf, len, &off, &plen) != ASW_FORMATION) return -1;
  Reader r{buf + off, plen};
  get_header(r, seq, stamp, frame_id, frame_cap);
  r.str(name, name_cap);
  uint32_t n = r.scalar<uint32_t>();
  r.bytes(points, (size_t)n * 3 * 8);
  r.bytes(adjmat, (size_t)n * n);
  uint8_t hg = r.scalar<uint8_t>();
  if (hg && gains) r.bytes(gains, (size_t)9 * n * n * 4);
  return r.ok ? 0 : -2;
}

// ---- CBAA ----
int64_t asw_encode_cbaa(uint32_t seq, double stamp, const char* frame_id,
                        uint32_t auction_id, uint32_t iter, uint32_t n,
                        const float* price, const int32_t* who, uint8_t* out,
                        uint64_t cap) {
  Writer w = begin_frame(out, cap);
  put_header(w, seq, stamp, frame_id);
  w.scalar<uint32_t>(auction_id);
  w.scalar<uint32_t>(iter);
  w.scalar<uint32_t>(n);
  w.bytes(price, (size_t)n * 4);
  w.bytes(who, (size_t)n * 4);
  return finish_frame(w, ASW_CBAA);
}

int asw_cbaa_n(const uint8_t* buf, uint64_t len, uint32_t* n) {
  uint64_t off, plen;
  if (asw_parse_frame(buf, len, &off, &plen) != ASW_CBAA) return -1;
  Reader r{buf + off, plen};
  get_header(r, nullptr, nullptr, nullptr, 0);
  r.scalar<uint32_t>();
  r.scalar<uint32_t>();
  uint32_t nn = r.scalar<uint32_t>();
  if (!r.ok) return -2;
  if (n) *n = nn;
  return 0;
}

int asw_decode_cbaa(const uint8_t* buf, uint64_t len, uint32_t* seq,
                    double* stamp, uint32_t* auction_id, uint32_t* iter,
                    float* price, int32_t* who) {
  uint64_t off, plen;
  if (asw_parse_frame(buf, len, &off, &plen) != ASW_CBAA) return -1;
  Reader r{buf + off, plen};
  get_header(r, seq, stamp, nullptr, 0);
  uint32_t aid = r.scalar<uint32_t>();
  uint32_t it = r.scalar<uint32_t>();
  uint32_t n = r.scalar<uint32_t>();
  if (auction_id) *auction_id = aid;
  if (iter) *iter = it;
  r.bytes(price, (size_t)n * 4);
  r.bytes(who, (size_t)n * 4);
  return r.ok ? 0 : -2;
}

// ---- VehicleEstimates ----
int64_t asw_encode_estimates(uint32_t seq, double stamp, const char* frame_id,
                             uint32_t n, const double* stamps /* n */,
                             const double* positions /* n*3 */, uint8_t* out,
                             uint64_t cap) {
  Writer w = begin_frame(out, cap);
  put_header(w, seq, stamp, frame_id);
  w.scalar<uint32_t>(n);
  for (uint32_t i = 0; i < n; ++i) {
    w.scalar<double>(stamps[i]);
    w.bytes(positions + (size_t)i * 3, 24);
  }
  return finish_frame(w, ASW_ESTIMATES);
}

int asw_estimates_n(const uint8_t* buf, uint64_t len, uint32_t* n) {
  uint64_t off, plen;
  if (asw_parse_frame(buf, len, &off, &plen) != ASW_ESTIMATES) return -1;
  Reader r{buf + off, plen};
  get_header(r, nullptr, nullptr, nullptr, 0);
  uint32_t nn = r.scalar<uint32_t>();
  if (!r.ok) return -2;
  if (n) *n = nn;
  return 0;
}

int asw_decode_estimates(const uint8_t* buf, uint64_t len, uint32_t* seq,
                         double* stamp, double* stamps, double* positions) {
  uint64_t off, plen;
  if (asw_parse_frame(buf, len, &off, &plen) != ASW_ESTIMATES) return -1;
  Reader r{buf + off, plen};
  get_header(r, seq, stamp, nullptr, 0);
  uint32_t n = r.scalar<uint32_t>();
  for (uint32_t i = 0; i < n && r.ok; ++i) {
    stamps[i] = r.scalar<double>();
    r.bytes(positions + (size_t)i * 3, 24);
  }
  return r.ok ? 0 : -2;
}

// ---- SafetyStatus ----
int64_t asw_encode_status(uint32_t seq, double stamp, const char* frame_id,
                          int active, uint8_t* out, uint64_t cap) {
  Writer w = begin_frame(out, cap);
  put_header(w, seq, stamp, frame_id);
  w.scalar<uint8_t>(active ? 1 : 0);
  return finish_frame(w, ASW_STATUS);
}

int asw_decode_status(const uint8_t* buf, uint64_t len, uint32_t* seq,
                      double* stamp, int* active) {
  uint64_t off, plen;
  if (asw_parse_frame(buf, len, &off, &plen) != ASW_STATUS) return -1;
  Reader r{buf + off, plen};
  get_header(r, seq, stamp, nullptr, 0);
  uint8_t a = r.scalar<uint8_t>();
  if (active) *active = a;
  return r.ok ? 0 : -2;
}

// ---- DistCmd (batched distcmd velocity goals) ----
int64_t asw_encode_distcmd(uint32_t seq, double stamp, const char* frame_id,
                           uint32_t n, const double* vel /* n*3 */,
                           uint8_t* out, uint64_t cap) {
  Writer w = begin_frame(out, cap);
  put_header(w, seq, stamp, frame_id);
  w.scalar<uint32_t>(n);
  w.bytes(vel, (size_t)n * 3 * 8);
  return finish_frame(w, ASW_DIST_CMD);
}

int asw_distcmd_n(const uint8_t* buf, uint64_t len, uint32_t* n) {
  uint64_t off, plen;
  if (asw_parse_frame(buf, len, &off, &plen) != ASW_DIST_CMD) return -1;
  Reader r{buf + off, plen};
  get_header(r, nullptr, nullptr, nullptr, 0);
  uint32_t nn = r.scalar<uint32_t>();
  if (!r.ok) return -2;
  if (n) *n = nn;
  return 0;
}

int asw_decode_distcmd(const uint8_t* buf, uint64_t len, uint32_t* seq,
                       double* stamp, double* vel) {
  uint64_t off, plen;
  if (asw_parse_frame(buf, len, &off, &plen) != ASW_DIST_CMD) return -1;
  Reader r{buf + off, plen};
  get_header(r, seq, stamp, nullptr, 0);
  uint32_t n = r.scalar<uint32_t>();
  r.bytes(vel, (size_t)n * 3 * 8);
  return r.ok ? 0 : -2;
}

// ---- Assignment (accepted permutation) ----
int64_t asw_encode_assignment(uint32_t seq, double stamp,
                              const char* frame_id, uint32_t n,
                              const int32_t* perm, uint8_t* out,
                              uint64_t cap) {
  Writer w = begin_frame(out, cap);
  put_header(w, seq, stamp, frame_id);
  w.scalar<uint32_t>(n);
  w.bytes(perm, (size_t)n * 4);
  return finish_frame(w, ASW_ASSIGNMENT);
}

int asw_assignment_n(const uint8_t* buf, uint64_t len, uint32_t* n) {
  uint64_t off, plen;
  if (asw_parse_frame(buf, len, &off, &plen) != ASW_ASSIGNMENT) return -1;
  Reader r{buf + off, plen};
  get_header(r, nullptr, nullptr, nullptr, 0);
  uint32_t nn = r.scalar<uint32_t>();
  if (!r.ok) return -2;
  if (n) *n = nn;
  return 0;
}

int asw_decode_assignment(const uint8_t* buf, uint64_t len, uint32_t* seq,
                          double* stamp, int32_t* perm) {
  uint64_t off, plen;
  if (asw_parse_frame(buf, len, &off, &plen) != ASW_ASSIGNMENT) return -1;
  Reader r{buf + off, plen};
  get_header(r, seq, stamp, nullptr, 0);
  uint32_t n = r.scalar<uint32_t>();
  r.bytes(perm, (size_t)n * 4);
  return r.ok ? 0 : -2;
}

// ---- FlightMode (operator GO/LAND/KILL broadcast) ----
int64_t asw_encode_flightmode(uint32_t seq, double stamp,
                              const char* frame_id, int mode, uint8_t* out,
                              uint64_t cap) {
  Writer w = begin_frame(out, cap);
  put_header(w, seq, stamp, frame_id);
  w.scalar<uint8_t>((uint8_t)mode);
  return finish_frame(w, ASW_FLIGHT_MODE);
}

int asw_decode_flightmode(const uint8_t* buf, uint64_t len, uint32_t* seq,
                          double* stamp, int* mode) {
  uint64_t off, plen;
  if (asw_parse_frame(buf, len, &off, &plen) != ASW_FLIGHT_MODE) return -1;
  Reader r{buf + off, plen};
  get_header(r, seq, stamp, nullptr, 0);
  uint8_t m = r.scalar<uint8_t>();
  if (mode) *mode = m;
  return r.ok ? 0 : -2;
}

// ---- SafetyStatusArray (batched per-vehicle ca-active flags) ----
int64_t asw_encode_safety_array(uint32_t seq, double stamp,
                                const char* frame_id, uint32_t n,
                                const uint8_t* active, uint8_t* out,
                                uint64_t cap) {
  Writer w = begin_frame(out, cap);
  put_header(w, seq, stamp, frame_id);
  w.scalar<uint32_t>(n);
  w.bytes(active, n);
  return finish_frame(w, ASW_SAFETY_ARRAY);
}

int asw_safety_array_n(const uint8_t* buf, uint64_t len, uint32_t* n) {
  uint64_t off, plen;
  if (asw_parse_frame(buf, len, &off, &plen) != ASW_SAFETY_ARRAY) return -1;
  Reader r{buf + off, plen};
  get_header(r, nullptr, nullptr, nullptr, 0);
  uint32_t nn = r.scalar<uint32_t>();
  if (!r.ok) return -2;
  if (n) *n = nn;
  return 0;
}

int asw_decode_safety_array(const uint8_t* buf, uint64_t len, uint32_t* seq,
                            double* stamp, uint8_t* active) {
  uint64_t off, plen;
  if (asw_parse_frame(buf, len, &off, &plen) != ASW_SAFETY_ARRAY) return -1;
  Reader r{buf + off, plen};
  get_header(r, seq, stamp, nullptr, 0);
  uint32_t n = r.scalar<uint32_t>();
  r.bytes(active, n);
  return r.ok ? 0 : -2;
}

}  // extern "C"
