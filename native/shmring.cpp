// Shared-memory message ring: the host-side transport of the wire API.
//
// The reference moves its four aclswarm_msgs over TCPROS loopback between
// the per-vehicle processes (SURVEY.md §5.8); this is the TPU framework's
// native equivalent for host-local traffic: a single-producer
// single-consumer lock-free byte ring in POSIX shared memory, carrying
// length-prefixed frames (typically the codec.cpp format). One ring per
// directed channel mirrors ROS's one-topic-one-publisher usage here; no
// locks, no syscalls on the hot path, and the "queue size 1 but don't
// want to lose any" intent of the reference's bid subscriptions
// (coordination_ros.cpp:417-418) becomes a real bounded FIFO with
// backpressure (write fails when full; caller decides to drop or retry).
//
// Memory layout (page 0 is the control block):
//   u32 magic, u32 capacity, u64 head (write cursor), u64 tail (read
//   cursor), both monotonically increasing byte offsets; data region
//   follows at offset 64. Messages are [u32 len][len bytes], contiguous;
//   a message never wraps — if it doesn't fit before the end, a u32
//   0xFFFFFFFF pad marker skips to the start (classic ring framing).
//
// SPSC correctness: producer only writes head, consumer only writes tail;
// release/acquire fences order payload writes against cursor publication.

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>

#include <fcntl.h>
#include <sys/file.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint32_t kMagic = 0x52575341u;  // "ASWR"
constexpr size_t kCtrl = 64;
constexpr uint32_t kPad = 0xFFFFFFFFu;

struct Ctrl {
  uint32_t magic;
  uint32_t capacity;
  std::atomic<uint64_t> head;
  std::atomic<uint64_t> tail;
};
static_assert(sizeof(Ctrl) <= kCtrl, "control block overflow");

struct Ring {
  Ctrl* ctrl;
  uint8_t* data;
  size_t map_len;
  bool owner;
  int lock_fd;  // owner keeps the shm fd open, flock-ed (liveness token)
  char name[256];
};

}  // namespace

extern "C" {

// Create (owner=1) or open (owner=0) a named ring; capacity is the data
// region size in bytes (power of two not required). Returns NULL on error.
void* asw_ring_open(const char* name, uint32_t capacity, int create) {
  capacity = (capacity + 3u) & ~3u;  // see alignment invariant below
  int flags = create ? (O_CREAT | O_EXCL | O_RDWR) : O_RDWR;
  int fd = shm_open(name, flags, 0600);
  if (fd < 0 && create && errno == EEXIST) {
    // An object with this name exists. The owner holds an flock on its
    // shm fd for its whole lifetime, so: lock acquired => owner crashed
    // without unlinking => reclaim; lock busy => live owner => fail
    // loudly (the O_EXCL guarantee, kept for the running case).
    int old_fd = shm_open(name, O_RDWR, 0600);
    if (old_fd < 0) return nullptr;
    if (flock(old_fd, LOCK_EX | LOCK_NB) != 0) {
      close(old_fd);  // someone alive owns it
      return nullptr;
    }
    close(old_fd);  // releases the probe lock
    shm_unlink(name);
    fd = shm_open(name, flags, 0600);
  }
  if (fd < 0) return nullptr;
  if (create && flock(fd, LOCK_EX | LOCK_NB) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  size_t len = kCtrl + capacity;
  if (create && ftruncate(fd, (off_t)len) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  if (!create) {
    struct stat st;
    if (fstat(fd, &st) != 0 || (size_t)st.st_size < kCtrl) {
      close(fd);
      return nullptr;
    }
    len = (size_t)st.st_size;
  }
  void* mem = mmap(nullptr, len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  Ring* r = new Ring;
  r->ctrl = (Ctrl*)mem;
  r->data = (uint8_t*)mem + kCtrl;
  r->map_len = len;
  r->owner = create != 0;
  if (create) {
    r->lock_fd = fd;  // keep open: holding the flock marks us alive
  } else {
    r->lock_fd = -1;
    close(fd);
  }
  std::snprintf(r->name, sizeof(r->name), "%s", name);
  if (create) {
    r->ctrl->capacity = capacity;
    r->ctrl->head.store(0, std::memory_order_relaxed);
    r->ctrl->tail.store(0, std::memory_order_relaxed);
    std::atomic_thread_fence(std::memory_order_release);
    r->ctrl->magic = kMagic;
  } else if (r->ctrl->magic != kMagic) {
    munmap(mem, len);
    delete r;
    return nullptr;
  }
  return r;
}

void asw_ring_close(void* h, int unlink_shm) {
  Ring* r = (Ring*)h;
  if (!r) return;
  munmap((void*)r->ctrl, r->map_len);
  if (unlink_shm) shm_unlink(r->name);
  if (r->lock_fd >= 0) close(r->lock_fd);  // releases the liveness flock
  delete r;
}

// Alignment invariant: capacity, every stored record (4-byte length word
// + payload padded to a 4-byte multiple), and the pad-marker skip are all
// multiples of 4 — so cursors mod capacity always leave >= 4 bytes before
// the wrap point and a length word never straddles it.

// Producer: append one message. Returns 0, or -1 if the ring is full
// (backpressure — caller retries or drops) or the message can never fit.
int asw_ring_write(void* h, const uint8_t* msg, uint32_t len) {
  Ring* r = (Ring*)h;
  uint32_t cap = r->ctrl->capacity;
  uint32_t stored = (len + 3u) & ~3u;
  uint64_t need = 4 + (uint64_t)stored;
  if (need > cap || len >= kPad) return -1;
  uint64_t head = r->ctrl->head.load(std::memory_order_relaxed);
  uint64_t tail = r->ctrl->tail.load(std::memory_order_acquire);
  size_t pos = head % cap;
  size_t room_to_end = cap - pos;
  if (room_to_end < need) {
    // wrap: pad marker skips the remainder, record restarts at offset 0
    if ((head - tail) + room_to_end + need > cap) return -1;
    std::memcpy(r->data + pos, &kPad, 4);
    head += room_to_end;
    pos = 0;
  } else if ((head - tail) + need > cap) {
    return -1;
  }
  std::memcpy(r->data + pos, &len, 4);
  std::memcpy(r->data + pos + 4, msg, len);
  r->ctrl->head.store(head + need, std::memory_order_release);
  return 0;
}

// Consumer: pop one message into out (cap bytes). Returns the message
// length, 0 if the ring is empty, or -1 if out is too small (message is
// left in the ring) / the ring is corrupt.
int64_t asw_ring_read(void* h, uint8_t* out, uint32_t out_cap) {
  Ring* r = (Ring*)h;
  uint32_t cap = r->ctrl->capacity;
  uint64_t tail = r->ctrl->tail.load(std::memory_order_relaxed);
  uint64_t head = r->ctrl->head.load(std::memory_order_acquire);
  while (true) {
    if (tail == head) return 0;
    size_t pos = tail % cap;
    uint32_t len;
    std::memcpy(&len, r->data + pos, 4);
    if (len == kPad) {
      tail += cap - pos;  // pad marker: skip to ring start
      continue;
    }
    uint32_t stored = (len + 3u) & ~3u;
    if (4 + (uint64_t)stored > head - tail) return -1;  // corrupt
    if (len > out_cap) return -1;
    std::memcpy(out, r->data + pos + 4, len);
    r->ctrl->tail.store(tail + 4 + stored, std::memory_order_release);
    return (int64_t)len;
  }
}

// Data-region capacity in bytes (as created — openers read the true size).
uint32_t asw_ring_capacity(void* h) {
  return ((Ring*)h)->ctrl->capacity;
}

// Diagnostics: bytes currently queued.
uint64_t asw_ring_used(void* h) {
  Ring* r = (Ring*)h;
  return r->ctrl->head.load(std::memory_order_acquire) -
         r->ctrl->tail.load(std::memory_order_acquire);
}

}  // extern "C"
